"""Z-only compare scan: filter rows by their resident z-keys alone.

Ref role: Z3Iterator / Z2Iterator (geomesa-index-api .../iterators —
[UNVERIFIED - empty reference mount]): the reference's hottest scan never
deserializes the feature — it bounds-checks the row KEY. The TPU analog
keeps the index key planes (uint32 hi/lo) resident and reads 8 bytes/row
instead of the 16 bytes/row of coordinate+time planes.

The kernel needs no de-interleave: Morton bit-spreading is monotonic per
dimension, so ``extract_d(z) ∈ [lo_d, hi_d]`` is exactly
``spread_d(lo_d) <= (z & dim_mask_d) <= spread_d(hi_d)`` — three ANDs and
six 64-bit compares per row, carried as uint32 hi/lo lane pairs (the TPU
VPU has no 64-bit integer lanes).

Time-binned Z3 keys (bin, z) get per-bin bounds: the query window maps to
one (possibly partial) offset range per period bin, and the mask is
``any_b(bin == b AND z within bounds_b)``. The bin count is static at
trace time; pad ``bin_ids`` with -1 (never matches) to bound recompiles.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.curves import zorder

U = np.uint64
_LO32 = U(0xFFFFFFFF)


def _hi_lo(v: np.ndarray) -> tuple[int, int]:
    hi, lo = zorder.u64_hi_lo(v)
    return int(hi), int(lo)


def _dim_bounds(qlo: tuple, qhi: tuple, split, max_mask: int, n_dims: int):
    """Per-dimension masked-compare bounds for one z cell box: per dim d
    the columns are (mask_hi, mask_lo, lo_hi, lo_lo, hi_hi, hi_lo), where
    mask keeps only dim d's interleaved bit positions and lo/hi are the
    spread (inclusive) cell bounds."""
    out = np.empty((n_dims, 6), np.uint32)
    for d in range(n_dims):
        mask = split(np.uint64(max_mask)) << U(d)
        blo = split(np.uint64(qlo[d])) << U(d)
        bhi = split(np.uint64(qhi[d])) << U(d)
        out[d, 0:2] = _hi_lo(mask)
        out[d, 2:4] = _hi_lo(blo)
        out[d, 4:6] = _hi_lo(bhi)
    return out


def z3_dim_bounds(qlo: tuple, qhi: tuple) -> np.ndarray:
    """(3, 6) uint32 bounds for one Z3 cell box (21-bit x/y/t corners)."""
    return _dim_bounds(qlo, qhi, zorder.split_3d_np, zorder.MAX_MASK_3D, 3)


def z2_dim_bounds(qlo: tuple, qhi: tuple) -> np.ndarray:
    """(2, 6) uint32 bounds for one Z2 cell box (31-bit x/y corners)."""
    return _dim_bounds(qlo, qhi, zorder.split_2d_np, zorder.MAX_MASK_2D, 2)


def _ge64(a_hi, a_lo, b_hi, b_lo):
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo >= b_lo))


def _le64(a_hi, a_lo, b_hi, b_lo):
    return (a_hi < b_hi) | ((a_hi == b_hi) & (a_lo <= b_lo))


def _dims_mask(z_hi, z_lo, bounds, n_dims: int):
    """AND of the per-dimension masked compares; bounds is (n_dims, 6)."""
    m = None
    for d in range(n_dims):
        mask_hi, mask_lo = bounds[d, 0], bounds[d, 1]
        zm_hi = z_hi & mask_hi
        zm_lo = z_lo & mask_lo
        md = _ge64(zm_hi, zm_lo, bounds[d, 2], bounds[d, 3]) & _le64(
            zm_hi, zm_lo, bounds[d, 4], bounds[d, 5]
        )
        m = md if m is None else (m & md)
    return m


def z3_zscan_mask(z_hi, z_lo, bins, bounds, bin_ids):
    """Boolean hit mask from key planes alone.

    z_hi/z_lo: uint32 (n,) key planes. bins: int32 (n,) period-bin plane.
    bounds: uint32 (B, 3, 6) per-bin dim bounds. bin_ids: int32 (B,), -1
    entries are padding and never match. B is static at trace time.
    """
    import jax.numpy as jnp

    total = jnp.zeros(z_hi.shape, bool)
    for b in range(bounds.shape[0]):
        total = total | (
            (bins == bin_ids[b]) & _dims_mask(z_hi, z_lo, bounds[b], 3)
        )
    return total


def z2_zscan_mask(z_hi, z_lo, bounds):
    """Boolean hit mask for unbinned Z2 keys; bounds is (2, 6) uint32."""
    return _dims_mask(z_hi, z_lo, bounds, 2)


def z3_query_bounds(
    sfc,
    xmin: float,
    ymin: float,
    xmax: float,
    ymax: float,
    tmin_ms: int,
    tmax_ms: int,
) -> tuple[np.ndarray, np.ndarray]:
    """(bounds (B,3,6), bin_ids (B,)) for a bbox + absolute-ms window.

    One entry per period bin the window touches; edge bins get partial
    offset ranges, interior bins the full offset span — the same per-bin
    decomposition Z3IndexKeySpace feeds its per-bin Z3SFC.ranges calls
    with (loose semantics: cell-granular, no residual refinement).
    """
    from geomesa_tpu.curves.binnedtime import bins_for_interval

    qx = (int(sfc.lon.normalize(xmin)), int(sfc.lon.normalize(xmax)))
    qy = (int(sfc.lat.normalize(ymin)), int(sfc.lat.normalize(ymax)))
    bounds, ids = [], []
    for b, lo_off, hi_off in bins_for_interval(tmin_ms, tmax_ms, sfc.period):
        qt = (
            int(sfc.time.normalize(lo_off)),
            int(sfc.time.normalize(hi_off)),
        )
        bounds.append(
            z3_dim_bounds((qx[0], qy[0], qt[0]), (qx[1], qy[1], qt[1]))
        )
        ids.append(b)
    if not bounds:  # empty/inverted window: zero bins, matches nothing
        return np.zeros((0, 3, 6), np.uint32), np.array([], np.int32)
    return np.stack(bounds), np.array(ids, np.int32)


# -- XZ (extent-curve) key scans ---------------------------------------------
#
# XZ codes are pre-order tree walks, not Morton interleaves, so there is no
# masked-compare trick: a query decomposes into a SMALL list of inclusive
# [lo, hi] code ranges (budget-bounded, over-covering on truncation — see
# curves/xz.py ranges()), and the device mask tests each row's hi/lo code
# lanes against every range. R is static at trace time; pad with
# never-matching entries (lo > hi) to bound recompiles.


def xz_range_bounds(ranges) -> np.ndarray:
    """IndexRange list -> (R, 4) uint32 rows [lo_hi, lo_lo, hi_hi, hi_lo]."""
    out = np.empty((len(ranges), 4), np.uint32)
    for i, r in enumerate(ranges):
        out[i, 0:2] = _hi_lo(np.uint64(r.lower))
        out[i, 2:4] = _hi_lo(np.uint64(r.upper))
    return out


_NEVER_RANGE = np.array(
    [0xFFFFFFFF, 0xFFFFFFFF, 0, 0], np.uint32
)  # lo = 2^64-1 > hi = 0: matches nothing


def pad_ranges(bounds: np.ndarray, min_r: int = 1) -> np.ndarray:
    """Pad the range axis (last-but-one) up to the compile-shape ladder
    (:mod:`geomesa_tpu.bucketing`; next power of two on the default
    ladder) with never-matching entries so jit sees a bounded set of R
    shapes."""
    from geomesa_tpu.bucketing import bucket_cap

    r = bounds.shape[-2]
    cap = max(min_r, bucket_cap(r))
    if cap == r:
        return bounds
    pad_shape = bounds.shape[:-2] + (cap - r, 4)
    return np.concatenate(
        [bounds, np.broadcast_to(_NEVER_RANGE, pad_shape)], axis=-2
    )


def xz_range_mask(xz_hi, xz_lo, bounds):
    """Boolean hit mask for unbinned XZ2 keys; bounds is (R, 4) uint32.

    One broadcasted compare over the range axis (not a Python unroll):
    the (R, n) intermediates fuse into the reduction, and the trace stays
    O(1) nodes regardless of R."""
    import jax.numpy as jnp

    zh, zl = xz_hi[None, :], xz_lo[None, :]
    ge = _ge64(zh, zl, bounds[:, 0:1], bounds[:, 1:2])
    le = _le64(zh, zl, bounds[:, 2:3], bounds[:, 3:4])
    return jnp.any(ge & le, axis=0)


def xz3_range_mask(xz_hi, xz_lo, bins, bounds, bin_ids):
    """Boolean hit mask for binned XZ3 keys.

    bounds: uint32 (B, R, 4) per-bin ranges; bin_ids: int32 (B,), -1 is
    padding and never matches. The bin axis unrolls (B <= 64, typically
    <= 8); the range axis is one broadcasted compare per bin.
    """
    import jax.numpy as jnp

    total = jnp.zeros(xz_hi.shape, bool)
    for b in range(bounds.shape[0]):
        total = total | (
            (bins == bin_ids[b]) & xz_range_mask(xz_hi, xz_lo, bounds[b])
        )
    return total


def xz2_query_bounds(
    sfc, xmin: float, ymin: float, xmax: float, ymax: float,
    max_ranges: int = 128,
) -> np.ndarray:
    """(R, 4) uint32 range bounds for one bbox (loose cell semantics: an
    over-covering superset; truncation at max_ranges stays a superset)."""
    return xz_range_bounds(sfc.ranges(xmin, ymin, xmax, ymax,
                                      max_ranges=max_ranges))


def xz3_query_bounds(
    sfc,
    xmin: float,
    ymin: float,
    xmax: float,
    ymax: float,
    tmin_ms: int,
    tmax_ms: int,
    max_ranges: int = 128,
) -> tuple[np.ndarray, np.ndarray]:
    """(bounds (B, R, 4), bin_ids (B,)) for a bbox + absolute-ms window.

    One entry per period bin, partial time extents on edge bins — the
    XZ3 analog of :func:`z3_query_bounds`; interior whole-period bins
    share one decomposition. Per-bin range lists are padded to a common R
    with never-matching entries.
    """
    from geomesa_tpu.curves.binnedtime import bins_for_interval, max_offset

    mx = max_offset(sfc.period)
    per_bin: list = []
    ids: list = []
    whole_cache = None
    # the spatial box is bin-invariant: build its arrays once, outside
    # the per-bin loop (only the time offsets vary per bin)
    ax, ay = np.array([xmin]), np.array([ymin])
    bx, by = np.array([xmax]), np.array([ymax])
    for b, lo_off, hi_off in bins_for_interval(tmin_ms, tmax_ms, sfc.period):
        whole = lo_off == 0 and hi_off == mx
        if whole and whole_cache is not None:
            rs = whole_cache
        else:
            rs = sfc.ranges(
                ax, ay,
                np.array([float(lo_off)]),  # lint: disable=GT004(host-side scalar range planning; no device arrays in this loop)
                bx, by,
                np.array([float(hi_off)]),  # lint: disable=GT004(host-side scalar range planning; no device arrays in this loop)
                max_ranges=max_ranges,
            )
            if whole:
                whole_cache = rs
        per_bin.append(xz_range_bounds(rs))
        ids.append(b)
    if not per_bin:
        return np.zeros((0, 1, 4), np.uint32), np.array([], np.int32)
    from geomesa_tpu.bucketing import bucket_cap

    longest = max(len(p) for p in per_bin)
    r_max = bucket_cap(longest)  # same ladder as pad_ranges
    stacked = np.stack([pad_ranges(p, min_r=r_max) for p in per_bin])
    return stacked, np.array(ids, np.int32)


# -- de-interleaved key-plane scans ------------------------------------------
#
# Morton order exists for SORTING (contiguous key ranges on disk / in the
# exchange); a resident SCAN is free to choose its own layout. Comparing
# the interleaved key needs ~46 VPU ops/row (three masked 64-bit compares
# in hi/lo lanes) and measures compute-bound on v5e; storing the SAME key
# de-interleaved — nx, ny uint32 planes plus ONE packed bt word
# ((bin - bin_base) << 21 | nt) — answers the identical cell-granular
# query with ~12 ops/row and reaches the roofline. 12B/row either way.
# Contiguous query bins merge into a single bt range, so a multi-week
# window costs 2 compares, not 2 per bin.

BT_TIME_BITS = 21  # nt occupies the low 21 bits of bt
BT_BIN_SPAN = 1 << (32 - BT_TIME_BITS)  # max bins representable (2^11)


def z3_dim_planes(sfc, nx, ny, nt, bins, bin_base: int):
    """Pack quantized dims + bins into the scan planes (host or device
    arrays; works under numpy and jnp, including inside jit).

    Rows whose ``bins - bin_base`` falls outside [0, BT_BIN_SPAN - 1) get
    the SENTINEL bt 0xFFFFFFFF — the top packable bin's space, which the
    query builder refuses to address — so out-of-window rows are
    deterministically unmatchable rather than silently wrapping into
    another bin's key space. Callers derive bin_base from the data's min
    bin (and fall back to the masked-compare planes for spans that do not
    fit)."""
    if sfc.precision != BT_TIME_BITS:
        # nt wider than 21 bits would silently bleed into the bin field
        raise ValueError(
            f"dim-plane packing requires precision {BT_TIME_BITS} "
            f"(got {sfc.precision}); use the masked-compare planes"
        )
    rel = (bins - bin_base).astype(nx.dtype)  # negatives wrap huge (u32)
    bt = (rel << BT_TIME_BITS) | nt
    oob = rel >= (BT_BIN_SPAN - 1)
    if hasattr(bt, "at") and not isinstance(bt, np.ndarray):  # jnp path
        import jax.numpy as jnp

        bt = jnp.where(oob, jnp.uint32(0xFFFFFFFF), bt)
    else:
        bt = np.where(oob, np.uint32(0xFFFFFFFF), bt)
    return nx, ny, bt


def z3_dim_plane_query(
    sfc,
    xmin: float,
    ymin: float,
    xmax: float,
    ymax: float,
    tmin_ms: int,
    tmax_ms: int,
    bin_base: int,
) -> "tuple[tuple, tuple, list] | None":
    """(qnx, qny, bt_ranges) for the dim-plane scan, or None when a query
    bin falls outside the packable window. Contiguous bins merge into
    single inclusive bt ranges."""
    from geomesa_tpu.curves.binnedtime import bins_for_interval

    if sfc.precision != BT_TIME_BITS:
        return None  # planes for this sfc cannot have been packed

    qnx = (int(sfc.lon.normalize(xmin)), int(sfc.lon.normalize(xmax)))
    qny = (int(sfc.lat.normalize(ymin)), int(sfc.lat.normalize(ymax)))
    ranges: list = []
    for b, lo_off, hi_off in bins_for_interval(tmin_ms, tmax_ms, sfc.period):
        rel = b - bin_base
        # top bin reserved: it is the out-of-window SENTINEL space of
        # z3_dim_planes and must never be addressable by a query
        if not (0 <= rel < BT_BIN_SPAN - 1):
            return None
        lo = (rel << BT_TIME_BITS) | int(sfc.time.normalize(lo_off))
        hi = (rel << BT_TIME_BITS) | int(sfc.time.normalize(hi_off))
        if ranges and lo == ranges[-1][1] + 1:
            ranges[-1] = (ranges[-1][0], hi)
        else:
            ranges.append((lo, hi))
    return qnx, qny, ranges


def z3_dim_plane_qarr(
    sfc,
    env,
    window,
    bin_base: int,
    bin_range: "tuple | None",
    max_ranges: int = 8,
) -> "tuple[np.ndarray, int] | None":
    """RUNTIME query vector for the dim-plane scan: uint32
    ``[qnx_lo, qnx_hi, qny_lo, qny_hi, (bt_lo, bt_hi) * R]`` with R padded
    to a power of two by inverted (never-matching) ranges. One compiled
    kernel per R bucket serves EVERY window — the serving path must not
    pay a recompile per viewport the way baked-constant kernels do.

    ``bin_range`` clamps to the bins actually staged (query bins outside
    it match nothing by construction). Returns None when a surviving
    query bin falls outside the packable window relative to ``bin_base``
    (the caller falls back to another engine) or when the merged range
    count exceeds ``max_ranges``.
    """
    from geomesa_tpu.curves.binnedtime import bins_for_interval

    if sfc.precision != BT_TIME_BITS:
        return None  # planes for this sfc cannot have been packed
    xmin, ymin, xmax, ymax = env
    qnx = (int(sfc.lon.normalize(xmin)), int(sfc.lon.normalize(xmax)))
    qny = (int(sfc.lat.normalize(ymin)), int(sfc.lat.normalize(ymax)))
    ranges: list = []
    for b, lo_off, hi_off in bins_for_interval(
        int(window[0]), int(window[1]), sfc.period
    ):
        if bin_range is not None and not (bin_range[0] <= b <= bin_range[1]):
            continue  # bin not staged: matches nothing
        rel = b - bin_base
        # top bin reserved: the out-of-window SENTINEL space of
        # z3_dim_planes must never be addressable by a query
        if not (0 <= rel < BT_BIN_SPAN - 1):
            return None
        lo = (rel << BT_TIME_BITS) | int(sfc.time.normalize(lo_off))
        hi = (rel << BT_TIME_BITS) | int(sfc.time.normalize(hi_off))
        if ranges and lo == ranges[-1][1] + 1:
            ranges[-1] = (ranges[-1][0], hi)
        else:
            ranges.append((lo, hi))
    if len(ranges) > max_ranges:
        return None
    from geomesa_tpu.bucketing import bucket_cap

    r = bucket_cap(len(ranges))  # same ladder as pad_ranges
    out = np.empty(4 + 2 * r, np.uint32)
    if ranges:
        out[0:4] = [qnx[0], qnx[1], qny[0], qny[1]]
    else:
        out[0:4] = [1, 0, 1, 0]  # inverted: matches nothing
    for k in range(r):
        lo, hi = ranges[k] if k < len(ranges) else (0xFFFFFFFF, 0)
        out[4 + 2 * k] = lo
        out[5 + 2 * k] = hi
    return out, r


def z2_dim_plane_qarr(sfc, env) -> np.ndarray:
    """RUNTIME query vector for the UNBINNED 2-plane dim scan: uint32
    ``[qnx_lo, qnx_hi, qny_lo, qny_hi]`` (the z2 analog of
    :func:`z3_dim_plane_qarr`; no bt ranges — the key has no time)."""
    xmin, ymin, xmax, ymax = env
    return np.array(
        [
            int(sfc.lon.normalize(xmin)), int(sfc.lon.normalize(xmax)),
            int(sfc.lat.normalize(ymin)), int(sfc.lat.normalize(ymax)),
        ],
        np.uint32,
    )


def z2_dimscan_mask_rt(nx, ny, qarr):
    """XLA-fused 2-plane dim mask with RUNTIME bounds (z2 schemas)."""
    m = (nx >= qarr[0]) & (nx <= qarr[1])
    return m & (ny >= qarr[2]) & (ny <= qarr[3])


def build_z2_dimscan_rt(
    *,
    block_rows: int = 1024,
    interpret: "bool | None" = None,
):
    """Pallas 2-plane dim kernel with RUNTIME bounds: (count_fn, mask_fn)
    over ``(qarr, nx, ny)`` — the z2 sibling of
    :func:`build_z3_dimscan_rt` (4 compares/row over 8B/row)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    LANES = 128
    br = block_rows
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    _zero = lambda: jnp.int32(0)  # noqa: E731 (int32 index-map literal)

    def _tile_mask(q_ref, nx_t, ny_t):
        m = (nx_t >= q_ref[0]) & (nx_t <= q_ref[1])
        return m & (ny_t >= q_ref[2]) & (ny_t <= q_ref[3])

    def _prep(nx, ny):
        n = int(nx.shape[0])
        grid = max(1, -(-n // (br * LANES)))
        pad = grid * br * LANES - n
        # never-match padding; see the z3 builder's rationale
        mats = [
            jnp.pad(a, (0, pad), constant_values=np.uint32(0xFFFFFFFF)).reshape(
                grid * br, LANES
            )
            for a in (nx, ny)
        ]
        return n, grid, mats

    def count_fn(qarr, nx, ny):
        n, grid, mats = _prep(nx, ny)

        def kernel(q_ref, a_ref, b_ref, out_ref):
            m = _tile_mask(q_ref, a_ref[...], b_ref[...])

            @pl.when(pl.program_id(0) == 0)
            def _():
                out_ref[...] = jnp.zeros((1, LANES), jnp.int32)

            out_ref[...] = out_ref[...] + jnp.sum(
                m.astype(jnp.int32), axis=0, dtype=jnp.int32, keepdims=True
            )

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((br, LANES), lambda i, q: (i, _zero()))
            ] * 2,
            out_specs=pl.BlockSpec(
                (1, LANES), lambda i, q: (_zero(), _zero())
            ),
        )
        partials = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            interpret=interpret,
        )(qarr, *mats)
        return jnp.sum(partials, dtype=jnp.int32)

    def mask_fn(qarr, nx, ny):
        n, grid, mats = _prep(nx, ny)

        def kernel(q_ref, a_ref, b_ref, out_ref):
            m = _tile_mask(q_ref, a_ref[...], b_ref[...])
            out_ref[...] = m.astype(jnp.int8)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((br, LANES), lambda i, q: (i, _zero()))
            ] * 2,
            out_specs=pl.BlockSpec((br, LANES), lambda i, q: (i, _zero())),
        )
        m = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((grid * br, LANES), jnp.int8),
            interpret=interpret,
        )(qarr, *mats)
        return m.reshape(-1)[:n].astype(bool)

    return count_fn, mask_fn


def z3_dimscan_mask_rt(nx, ny, bt, qarr, n_ranges: int):
    """XLA-fused dim-plane mask with RUNTIME bounds (the fused-agg /
    streaming engine; the Pallas kernel below is the count champion).
    ``qarr`` is the vector from :func:`z3_dim_plane_qarr`; ``n_ranges``
    is static (one trace per R bucket)."""
    import jax.numpy as jnp

    m = (nx >= qarr[0]) & (nx <= qarr[1])
    m &= (ny >= qarr[2]) & (ny <= qarr[3])
    tm = None
    for k in range(n_ranges):
        r = (bt >= qarr[4 + 2 * k]) & (bt <= qarr[5 + 2 * k])
        tm = r if tm is None else (tm | r)
    return m & tm


def build_z3_dimscan_rt(
    n_ranges: int,
    *,
    block_rows: int = 1024,
    interpret: "bool | None" = None,
    extra_planes: int = 0,
):
    """Pallas dim-plane kernel with RUNTIME query bounds: (count_fn,
    mask_fn) over ``(qarr, nx, ny, bt)``. The query vector rides in SMEM
    via scalar prefetch, so ONE compiled kernel (per power-of-two R
    bucket) serves every window — the serving-path requirement the
    baked-constant builder below cannot meet. Same measured tiling as
    :func:`build_z3_dimscan_pallas` (block_rows=512, 128 lanes).

    ``extra_planes`` is a MEASUREMENT control, not a serving feature: it
    threads that many extra uint32 planes through the kernel whose
    values fold into the mask data-dependently (so Mosaic cannot elide
    the reads) but never change the result for nonzero fill. Padding
    the 12B/row kernel to 16B/row this way settles whether the scan is
    bandwidth-bound or row-rate-bound (VERDICT r4 next-6): if rows/s
    holds while bytes/row grows, the bound is per-row VPU ops, and the
    12B kernel's lower HBM%% is arithmetic, not inefficiency.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    LANES = 128
    br = block_rows
    E = int(extra_planes)
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    _zero = lambda: jnp.int32(0)  # noqa: E731 (int32 index-map literal)

    def _tile_mask(q_ref, nx_t, ny_t, bt_t, *extra_t):
        m = (nx_t >= q_ref[0]) & (nx_t <= q_ref[1])
        m &= (ny_t >= q_ref[2]) & (ny_t <= q_ref[3])
        tm = None
        for k in range(n_ranges):
            r = (bt_t >= q_ref[4 + 2 * k]) & (bt_t <= q_ref[5 + 2 * k])
            tm = r if tm is None else (tm | r)
        m = m & tm
        for e_t in extra_t:
            # data-dependent fold (always true for the nonzero fill the
            # caller provides) — the read cannot be optimized away
            m = m & (e_t != jnp.uint32(0))
        return m

    def _prep(nx, ny, bt, extra):
        n = int(nx.shape[0])
        grid = max(1, -(-n // (br * LANES)))
        pad = grid * br * LANES - n
        # NEVER-MATCH padding (0xFFFFFFFF > any 21-bit query bound, and
        # the bt sentinel space is unaddressable by construction) instead
        # of a per-tile row-index tail mask: the kernel is VPU-bound at
        # ~52B rows/s, and the tail's iota+compare cost ~4 ops of the
        # ~17/row -- dropping it buys ~20% (measured 626 -> 745 GB/s)
        mats = [
            jnp.pad(a, (0, pad), constant_values=np.uint32(0xFFFFFFFF)).reshape(
                grid * br, LANES
            )
            for a in (nx, ny, bt) + tuple(extra)
        ]
        return n, grid, mats

    def count_fn(qarr, nx, ny, bt, *extra):
        assert len(extra) == E
        n, grid, mats = _prep(nx, ny, bt, extra)

        def kernel(q_ref, *refs):
            out_ref = refs[-1]
            m = _tile_mask(q_ref, *(r[...] for r in refs[:-1]))

            @pl.when(pl.program_id(0) == 0)
            def _():
                out_ref[...] = jnp.zeros((1, LANES), jnp.int32)

            out_ref[...] = out_ref[...] + jnp.sum(
                m.astype(jnp.int32), axis=0, dtype=jnp.int32, keepdims=True
            )

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            # index maps receive the prefetched scalar ref as a trailing
            # arg; literal indices must be int32 (a raw Python 0 traces
            # to an i64 constant under x64, which Mosaic cannot legalize)
            in_specs=[
                pl.BlockSpec((br, LANES), lambda i, q: (i, _zero()))
            ] * (3 + E),
            out_specs=pl.BlockSpec(
                (1, LANES), lambda i, q: (_zero(), _zero())
            ),
        )
        partials = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            interpret=interpret,
        )(qarr, *mats)
        return jnp.sum(partials, dtype=jnp.int32)

    def mask_fn(qarr, nx, ny, bt, *extra):
        assert len(extra) == E
        n, grid, mats = _prep(nx, ny, bt, extra)

        def kernel(q_ref, *refs):
            out_ref = refs[-1]
            # padding rows never match (see _prep); [:n] slices them off
            m = _tile_mask(q_ref, *(r[...] for r in refs[:-1]))
            out_ref[...] = m.astype(jnp.int8)

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((br, LANES), lambda i, q: (i, _zero()))
            ] * (3 + E),
            out_specs=pl.BlockSpec((br, LANES), lambda i, q: (i, _zero())),
        )
        m = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((grid * br, LANES), jnp.int8),
            interpret=interpret,
        )(qarr, *mats)
        return m.reshape(-1)[:n].astype(bool)

    return count_fn, mask_fn


def _dim_tile_mask(qnx, qny, bt_ranges):
    import jax.numpy as jnp

    def tile_mask(nx_t, ny_t, bt_t):
        m = (nx_t >= jnp.uint32(qnx[0])) & (nx_t <= jnp.uint32(qnx[1]))
        m &= (ny_t >= jnp.uint32(qny[0])) & (ny_t <= jnp.uint32(qny[1]))
        tm = None
        for lo, hi in bt_ranges:
            r = (bt_t >= jnp.uint32(lo)) & (bt_t <= jnp.uint32(hi))
            tm = r if tm is None else (tm | r)
        if tm is None:  # empty window
            tm = jnp.zeros(nx_t.shape, bool)
        return m & tm

    return tile_mask


def z3_dimscan_mask(nx, ny, bt, qnx, qny, bt_ranges):
    """XLA-fused dim-plane mask (CI / cross-check engine; the Pallas tile
    kernel below is the TPU bandwidth champion)."""
    return _dim_tile_mask(qnx, qny, bt_ranges)(nx, ny, bt)


def build_z3_dimscan_pallas(
    qnx,
    qny,
    bt_ranges,
    *,
    block_rows: int = 512,
    interpret: "bool | None" = None,
):
    """BAKED-CONSTANT Pallas tile kernel over the de-interleaved key
    planes: (count_fn, mask_fn) over (nx, ny, bt) uint32 1-D device
    planes, query bounds compiled in as uint32 constants.

    Kept as a cross-check engine (tests compare it against the
    runtime-bounds kernel and the XLA mask). SERVING uses
    :func:`build_z3_dimscan_rt` instead — same tiling and speed (runtime
    bounds measured within noise of baked constants), but one compile
    per range bucket serves every window where this builder pays a
    compile per distinct query.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    LANES = 128
    br = block_rows
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    tile_mask = _dim_tile_mask(qnx, qny, bt_ranges)

    _zero = lambda: jnp.int32(0)  # noqa: E731 (int32 index-map literal)
    in_specs = [pl.BlockSpec((br, LANES), lambda i: (i, _zero()))] * 3

    def _prep(nx, ny, bt):
        n = int(nx.shape[0])
        grid = max(1, -(-n // (br * LANES)))
        pad = grid * br * LANES - n
        mats = [
            jnp.pad(a, (0, pad)).reshape(grid * br, LANES)
            for a in (nx, ny, bt)
        ]
        return n, grid, mats

    def _tail(n):
        def apply(m):
            i = pl.program_id(0)
            idx = (
                i * br * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 0) * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 1)
            )
            return m & (idx < n)

        return apply

    def count_fn(nx, ny, bt):
        n, grid, mats = _prep(nx, ny, bt)
        tail = _tail(n)

        def kernel(a_ref, b_ref, c_ref, out_ref):
            m = tail(tile_mask(a_ref[...], b_ref[...], c_ref[...]))

            @pl.when(pl.program_id(0) == 0)
            def _():
                out_ref[...] = jnp.zeros((1, LANES), jnp.int32)

            out_ref[...] = out_ref[...] + jnp.sum(
                m.astype(jnp.int32), axis=0, dtype=jnp.int32, keepdims=True
            )

        partials = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, LANES), lambda i: (_zero(), _zero())),
            out_shape=jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            interpret=interpret,
        )(*mats)
        return jnp.sum(partials, dtype=jnp.int32)

    def mask_fn(nx, ny, bt):
        n, grid, mats = _prep(nx, ny, bt)
        tail = _tail(n)

        def kernel(a_ref, b_ref, c_ref, out_ref):
            m = tail(tile_mask(a_ref[...], b_ref[...], c_ref[...]))
            out_ref[...] = m.astype(jnp.int8)

        m = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((br, LANES), lambda i: (i, _zero())),
            out_shape=jax.ShapeDtypeStruct((grid * br, LANES), jnp.int8),
            interpret=interpret,
        )(*mats)
        return m.reshape(-1)[:n].astype(bool)

    return count_fn, mask_fn


def kind_mask_fn(kind: str):
    """Key-plane mask function for an index-key kind — the ONE dispatch
    table shared by the direct loose path and the fused-stats closure
    (binned kinds take (hi, lo, bins, bounds, ids); unbinned (hi, lo,
    bounds))."""
    return {
        "z3": z3_zscan_mask,
        "z2": z2_zscan_mask,
        "xz3": xz3_range_mask,
        "xz2": xz_range_mask,
    }[kind]


def batched_kind_mask(kind: str):
    """Q-stacked variant of :func:`kind_mask_fn` for micro-batch scan
    fusion (the device query scheduler): the query bounds/ids gain a
    leading query axis and the key planes broadcast, so Q compatible
    queries resolve in ONE device launch returning a (Q, n) hit matrix.
    Binned kinds take (hi, lo, bins, bounds[Q,...], ids[Q, B]); unbinned
    (hi, lo, bounds[Q, ...])."""
    import jax

    mf = kind_mask_fn(kind)
    if kind in ("z3", "xz3"):
        return jax.vmap(mf, in_axes=(None, None, None, 0, 0))
    return jax.vmap(mf, in_axes=(None, None, 0))


def batched_dim_mask_rt(n_ranges: int):
    """Q-stacked dim-plane mask with runtime bounds: ``qmat`` is the
    (Q, 4 + 2R) stack of :func:`z3_dim_plane_qarr` vectors (or (Q, 4)
    :func:`z2_dim_plane_qarr` vectors when ``n_ranges == 0``) and the
    result is (Q, n). The scheduler's fusion path uses the XLA engine —
    the per-query Pallas SMEM prefetch does not batch — which is
    cross-checked against the Pallas count champion elsewhere."""
    import jax

    if n_ranges == 0:
        return jax.vmap(z2_dimscan_mask_rt, in_axes=(None, None, 0))
    return jax.vmap(
        lambda nx, ny, bt, q: z3_dimscan_mask_rt(nx, ny, bt, q, n_ranges),
        in_axes=(None, None, None, 0),
    )


def build_z3_pallas_scan(
    bounds: np.ndarray,
    bin_ids: np.ndarray,
    *,
    block_rows: "int | None" = None,
    interpret: "bool | None" = None,
):
    """BAKED-CONSTANT Pallas kernel for the INTERLEAVED masked-compare
    key scan: (count_fn, mask_fn) over (bins int32, z_hi uint32, z_lo
    uint32) 1-D device planes — a cross-check engine for the interleaved
    layout (the resident cache serves z3/z2 from dim planes via
    build_z3_dimscan_rt; the interleaved layout remains for xz kinds and
    wide-bin-span schemas, served by the XLA kind_mask_fn path).

    Query bounds bake in as uint32 constants; padded bin entries
    (id < 0) are skipped at trace time, costing nothing. Same tiling discipline as
    ops/pallas_scan.py: (block_rows, 128) tiles DMA'd HBM->VMEM, a
    (1, 128) revisited accumulator tile for the count (TPU grids run
    sequentially per core), tail mask so padding rows never count, and
    interpret mode off-TPU so CI runs the identical kernel code.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    LANES = 128
    br = block_rows or 512
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"
    entries = [
        (int(bin_ids[b]), [[int(v) for v in bounds[b, d]] for d in range(3)])
        for b in range(len(bin_ids))
        if int(bin_ids[b]) >= 0
    ]

    def tile_mask(bins_t, zh_t, zl_t):
        m = None
        for bid, dims in entries:
            mb = bins_t == jnp.int32(bid)
            for mask_hi, mask_lo, lo_hi, lo_lo, hi_hi, hi_lo in dims:
                zm_hi = zh_t & jnp.uint32(mask_hi)
                zm_lo = zl_t & jnp.uint32(mask_lo)
                ge = (zm_hi > jnp.uint32(lo_hi)) | (
                    (zm_hi == jnp.uint32(lo_hi)) & (zm_lo >= jnp.uint32(lo_lo))
                )
                le = (zm_hi < jnp.uint32(hi_hi)) | (
                    (zm_hi == jnp.uint32(hi_hi)) & (zm_lo <= jnp.uint32(hi_lo))
                )
                mb = mb & ge & le
            m = mb if m is None else (m | mb)
        if m is None:  # all bins padded out: constant-false scan
            m = jnp.zeros(bins_t.shape, bool)
        return m

    _zero = lambda: jnp.int32(0)  # noqa: E731 (int32 index-map literal)
    in_specs = [pl.BlockSpec((br, LANES), lambda i: (i, _zero()))] * 3

    def _prep(bins, z_hi, z_lo):
        n = int(bins.shape[0])
        grid = max(1, -(-n // (br * LANES)))
        pad = grid * br * LANES - n
        mats = [
            jnp.pad(a, (0, pad)).reshape(grid * br, LANES)
            for a in (bins, z_hi, z_lo)
        ]
        return n, grid, mats

    def _tail(n):
        def apply(m):
            i = pl.program_id(0)
            idx = (
                i * br * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 0) * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 1)
            )
            return m & (idx < n)

        return apply

    def count_fn(bins, z_hi, z_lo):
        n, grid, mats = _prep(bins, z_hi, z_lo)
        tail = _tail(n)

        def kernel(b_ref, zh_ref, zl_ref, out_ref):
            m = tail(tile_mask(b_ref[...], zh_ref[...], zl_ref[...]))

            @pl.when(pl.program_id(0) == 0)
            def _():
                out_ref[...] = jnp.zeros((1, LANES), jnp.int32)

            out_ref[...] = out_ref[...] + jnp.sum(
                m.astype(jnp.int32), axis=0, dtype=jnp.int32, keepdims=True
            )

        partials = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, LANES), lambda i: (_zero(), _zero())),
            out_shape=jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            interpret=interpret,
        )(*mats)
        return jnp.sum(partials, dtype=jnp.int32)

    def mask_fn(bins, z_hi, z_lo):
        n, grid, mats = _prep(bins, z_hi, z_lo)
        tail = _tail(n)

        def kernel(b_ref, zh_ref, zl_ref, out_ref):
            m = tail(tile_mask(b_ref[...], zh_ref[...], zl_ref[...]))
            out_ref[...] = m.astype(jnp.int8)

        m = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((br, LANES), lambda i: (i, _zero())),
            out_shape=jax.ShapeDtypeStruct((grid * br, LANES), jnp.int8),
            interpret=interpret,
        )(*mats)
        return m.reshape(-1)[:n].astype(bool)

    return count_fn, mask_fn


def pad_bins(bounds: np.ndarray, bin_ids: np.ndarray, min_b: int = 1):
    """Pad the bin axis up to the compile-shape ladder (>= min_b; next
    power of two on the default ladder) so jit sees a bounded set of B
    shapes; pad ids are -1 (match nothing)."""
    from geomesa_tpu.bucketing import bucket_cap

    b = len(bin_ids)
    cap = max(min_b, bucket_cap(b))
    if cap == b:
        return bounds, bin_ids
    pb = np.zeros((cap,) + bounds.shape[1:], bounds.dtype)
    pb[:b] = bounds
    pi = np.full(cap, -1, np.int32)
    pi[:b] = bin_ids
    return pb, pi
