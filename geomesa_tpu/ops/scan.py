"""Columnar device scan: stage columns, evaluate fused masks.

The jitted mask function is the rebuild's Z3Iterator+FilterTransformIterator:
one fused elementwise kernel over resident columns producing a boolean mask
(XLA fuses the compare chain into a single HBM pass). Callers jit the
compiled device_fn once per query and apply it per partition so XLA caches
the executable across same-shaped partitions.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch


def _default_platform() -> str:
    import jax

    return jax.devices()[0].platform


def stage_columns_host(
    batch: FeatureBatch,
    names: "list[str]",
    start: int = 0,
    stop: "int | None" = None,
    dtype=None,
):
    """Host-side half of :func:`stage_columns`: the named planes as
    contiguous numpy arrays in their DEVICE storage dtypes, ready for
    upload. Split out so the resident cache can batch every 4-byte plane
    into one packed transfer (device_cache._stage_packed) instead of one
    round trip per plane."""
    from geomesa_tpu.ops.int64lanes import split_array_np

    stop = len(batch) if stop is None else stop
    out = {}
    splits: dict = {}  # attr -> (hi, lo), computed once per i64 column
    for name in names:
        if name.endswith(("__x0", "__y0", "__x1", "__y1")):
            # per-row envelope planes of a non-point geometry column
            attr = name[:-4]
            bb = batch.bboxes(attr)
            k = {"x0": 0, "y0": 1, "x1": 2, "y1": 3}[name[-2:]]
            arr = bb[start:stop, k]
        elif name.endswith("__x") or name.endswith("__y"):
            attr = name[:-3]
            col = batch.column(attr)
            arr = col[start:stop, 0 if name.endswith("__x") else 1]
        elif name.endswith("__hi") or name.endswith("__lo"):
            attr = name[:-4]
            if attr not in splits:
                splits[attr] = split_array_np(batch.column(attr)[start:stop])
            arr = splits[attr][0 if name.endswith("__hi") else 1]
        else:
            arr = batch.column(name)[start:stop]
        if dtype is not None and arr.dtype.kind == "f":
            arr = arr.astype(dtype)
        if arr.dtype == np.float64 and _default_platform() == "tpu":
            # TPU storage format is float32 lanes (README design stance):
            # the chip has no f64, and under x64 a float64 operand cannot
            # feed the Mosaic kernels. Explicit, not a silent jnp downcast.
            arr = arr.astype(np.float32)
        if arr.dtype in (np.int64, np.uint64):
            # Residual int64 columns (non-split callers) need x64 lanes, else
            # jax silently downcasts to int32 and ms literals overflow.
            from geomesa_tpu.jaxconf import require_x64

            require_x64()
        out[name] = np.ascontiguousarray(arr)
    return out


def stage_columns(
    batch: FeatureBatch,
    names: "list[str]",
    start: int = 0,
    stop: "int | None" = None,
    dtype=None,
):
    """Slice + upload the named device columns ("attr" scalar columns,
    "attr__x"/"attr__y" point coordinates, "attr__hi"/"attr__lo" two-word
    planes of int64 columns -- ops/int64lanes.py) as jax arrays."""
    import jax.numpy as jnp

    return {
        k: jnp.asarray(v)
        for k, v in stage_columns_host(
            batch, names, start=start, stop=stop, dtype=dtype
        ).items()
    }
