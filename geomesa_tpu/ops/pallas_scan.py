"""Pallas TPU kernel for the fused predicate scan (count + mask).

This is the rebuild's server-side hot loop -- the reference's per-KV
``Z3Iterator.accept`` + ``FilterTransformIterator`` predicate evaluation
(geomesa-accumulo .../iterators/Z3Iterator.scala, FilterTransformIterator
[UNVERIFIED - empty reference mount]) -- expressed as one Pallas kernel:
each grid step DMAs a (block_rows, 128) tile of every referenced column
HBM->VMEM, evaluates the whole conjunction on the VPU in one pass, and
emits either a per-tile hit count (SMEM scalar) or the boolean mask tile.
One HBM read per byte of scanned data; no intermediate materialization.

Columns reaching the kernel are 32-bit lanes only: float32/int32/uint32
scalars, point coords as ``__x``/``__y`` float32, and int64 (Date/Long)
columns pre-split into ``__hi``/``__lo`` word planes (ops/int64lanes.py).
Filters whose device part needs anything else (float64 columns, huge
polygon edge lists) fall back to the XLA-fused jnp path in
filter/compile.py -- same semantics, same staged columns.

On CPU jax (tests / CI) the kernel runs in interpret mode, so the whole
suite exercises the identical kernel code without a TPU.
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.ops.int64lanes import cmp_jax

LANES = 128
# Unrolled edge budget for in-kernel point-in-polygon; bigger rings fall
# back to the jnp path (broadcasting (n, E) there is fine in HBM).
MAX_KERNEL_EDGES = 64
_VMEM_BUDGET = 6 * 1024 * 1024


class PallasUnsupported(Exception):
    """Filter shape not expressible in the tile kernel; use device_fn."""


def _check(cond, why: str):
    if not cond:
        raise PallasUnsupported(why)


def supported_columns(f: ast.Filter, sft: SimpleFeatureType) -> list[str]:
    """Device columns the kernel will read; raises PallasUnsupported."""
    from geomesa_tpu.filter.compile import device_columns_for

    cols = device_columns_for(f, sft)
    for c in cols:
        if c.endswith(("__x", "__y", "__hi", "__lo",
                       "__x0", "__y0", "__x1", "__y1")):
            continue
        dtype = sft.descriptor(c).column_dtype
        _check(
            dtype in (np.float32, np.int32, np.float64),
            f"column {c}: dtype {dtype} not 32-bit-lane representable",
        )
        # float64 attribute columns are staged as-is for the jnp path; the
        # kernel would need a f32 downcast that can flip boundary compares.
        _check(dtype != np.float64, f"column {c} is float64")
    return cols


def _build_tile_fn(f: ast.Filter, sft: SimpleFeatureType):
    """AST -> fn(cols: dict[str, 2-D tile]) -> bool tile. Mirrors
    filter/compile.build_device_fn but restricted to ops that lower to
    Pallas TPU (elementwise VPU work on 32-bit lanes, static unrolls)."""

    def rec(node):
        import jax.numpy as jnp

        if node is ast.Include:
            return lambda cols: jnp.full(_tile_shape(cols), True, dtype=bool)
        if node is ast.Exclude:
            return lambda cols: jnp.full(_tile_shape(cols), False, dtype=bool)
        if isinstance(node, (ast.And, ast.Or)):
            fns = [rec(c) for c in node.children]
            is_and = isinstance(node, ast.And)

            def f_bool(cols, fns=fns, is_and=is_and):
                m = fns[0](cols)
                for fn in fns[1:]:
                    m = (m & fn(cols)) if is_and else (m | fn(cols))
                return m

            return f_bool
        if isinstance(node, ast.Not):
            fn = rec(node.child)
            return lambda cols, fn=fn: ~fn(cols)
        if isinstance(node, ast.BBox):
            if not sft.descriptor(node.attr).is_point:
                # envelope-overlap tile: delegate so the compare stays
                # bit-identical to the XLA path (single source, same as
                # the During/Compare delegation below)
                from geomesa_tpu.filter.compile import build_device_fn

                inner = build_device_fn(node, sft)
                return lambda cols, inner=inner: inner(cols)
            ax, ay = f"{node.attr}__x", f"{node.attr}__y"

            def f_bbox(cols, node=node, ax=ax, ay=ay):
                x, y = cols[ax], cols[ay]
                return (
                    (x >= node.xmin)
                    & (x <= node.xmax)
                    & (y >= node.ymin)
                    & (y <= node.ymax)
                )

            return f_bbox
        if isinstance(node, ast.DWithin):
            from geomesa_tpu.geom import Point

            if not (
                sft.descriptor(node.attr).is_point
                and isinstance(node.geometry, Point)
            ):
                # padded-envelope bbox: delegate to the single XLA-path
                # implementation (build_device_fn rewrites to BBox)
                from geomesa_tpu.filter.compile import build_device_fn

                inner = build_device_fn(node, sft)
                return lambda cols, inner=inner: inner(cols)
            ax, ay = f"{node.attr}__x", f"{node.attr}__y"

            def f_dw(cols, node=node, ax=ax, ay=ay):
                dx = cols[ax] - node.geometry.x
                dy = cols[ay] - node.geometry.y
                return dx * dx + dy * dy <= node.distance**2

            return f_dw
        if isinstance(node, ast.Intersects):
            _check(
                sft.descriptor(node.attr).is_point
                and hasattr(node.geometry, "rings")
                and node.op in ("intersects", "within", "disjoint"),
                "intersects shape not kernelizable",
            )
            from geomesa_tpu.geom.predicates import polygon_edges

            x1, y1, x2, y2 = polygon_edges(node.geometry.rings())
            _check(
                len(x1) <= MAX_KERNEL_EDGES,
                f"{len(x1)} polygon edges > kernel unroll budget",
            )
            edges = [
                (float(a), float(b), float(c), float(d))
                for a, b, c, d in zip(x1, y1, x2, y2)
            ]
            ax, ay = f"{node.attr}__x", f"{node.attr}__y"
            neg = node.op == "disjoint"

            def f_pip(cols, edges=edges, ax=ax, ay=ay, neg=neg):
                # crossing-number test, edges unrolled as scalar constants
                px, py = cols[ax], cols[ay]
                crossings = jnp.zeros(px.shape, dtype=jnp.int32)
                for ex1, ey1, ex2, ey2 in edges:
                    straddle = (ey1 > py) != (ey2 > py)
                    denom = (ey2 - ey1) if ey2 != ey1 else 1.0
                    xint = ex1 + (py - ey1) * (ex2 - ex1) / denom
                    crossings = crossings + (straddle & (px < xint))
                # parity via bitwise AND: `crossings % 2` trips an
                # infinite _convert_element_type recursion in the Mosaic
                # lowering when x64 is enabled (the weak int literal
                # round-trips through i64) — pinned by
                # tests/test_pallas_scan.py::test_mosaic_mod_recursion_repro
                m = (crossings & 1) == 1
                return ~m if neg else m

            return f_pip
        if isinstance(node, (ast.During, ast.Between, ast.Compare, ast.In)):
            # identical numeric semantics to build_device_fn -- delegate so
            # the i64 hi/lo rewrite and float-bound rounding stay in one
            # place (the inner closures are pure elementwise jnp).
            from geomesa_tpu.filter.compile import (
                _device_supported,
                build_device_fn,
            )

            _check(_device_supported(node, sft), f"{type(node).__name__}")
            inner = build_device_fn(node, sft)
            return lambda cols, inner=inner: inner(cols)
        raise PallasUnsupported(f"node {type(node).__name__}")

    import jax.numpy as jnp  # noqa: F401 (closures above)

    return rec(f)


def _tile_shape(cols: dict):
    return next(iter(cols.values())).shape


def _pick_block_rows(n_cols: int) -> int:
    rows = _VMEM_BUDGET // max(1, n_cols * LANES * 4)
    rows = max(64, min(1024, rows))
    return (rows // 32) * 32  # int8/int32 sublane multiple


def build_pallas_scan(
    f: ast.Filter,
    sft: SimpleFeatureType,
    *,
    block_rows: int | None = None,
    interpret: bool | None = None,
):
    """Compile the filter's device part to Pallas count/mask callables.

    Returns ``(count_fn, mask_fn, cols)`` where each fn takes a dict of
    staged 1-D device columns (see ops/scan.stage_columns) and returns the
    int32 hit count / bool mask for the whole array. Raises
    PallasUnsupported when the filter can't be tiled; callers fall back to
    CompiledFilter.device_fn.
    """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    cols = supported_columns(f, sft)
    _check(bool(cols), "no device columns (constant filter)")
    tile_fn = _build_tile_fn(f, sft)
    br = block_rows or _pick_block_rows(len(cols))
    if interpret is None:
        interpret = jax.devices()[0].platform != "tpu"

    def _prep(coldict):
        n = int(_tile_shape(coldict)[0])
        if n > 2**31 - 1 - br * LANES:
            raise PallasUnsupported("partition too large for int32 indexing")
        grid = max(1, -(-n // (br * LANES)))
        pad = grid * br * LANES - n
        mats = [
            jnp.pad(coldict[c], (0, pad)).reshape(grid * br, LANES)
            for c in cols
        ]
        return n, grid, pad, mats

    def _valid_mask(n):
        # rows past n (tile padding) must not count as hits
        def tail(m):
            i = pl.program_id(0)
            idx = (
                i * br * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 0) * LANES
                + jax.lax.broadcasted_iota(jnp.int32, (br, LANES), 1)
            )
            return m & (idx < n)

        return tail

    # index-map literals must be int32: under x64 a bare python 0 traces
    # as an i64 constant, which Mosaic refuses to lower
    _zero = lambda: jnp.int32(0)
    _in_specs = [
        pl.BlockSpec((br, LANES), lambda i: (i, _zero())) for _ in cols
    ]

    def count_fn(coldict):
        n, grid, pad, mats = _prep(coldict)
        tail = _valid_mask(n)

        def kernel(*refs):
            # TPU grids run sequentially per core, so a single (1, LANES)
            # output revisited by every step is a race-free accumulator.
            # Per-LANE partials, NOT a scalar: a scalar-output reduce takes
            # Mosaic's proxy path, which re-traces jnp.sum at LOWERING
            # time under the global dtype config -- with x64 enabled that
            # injects an int64 convert Mosaic cannot lower. The axis-0
            # reduce keeps a (1, LANES) vector and lowers directly.
            *in_refs, out_ref = refs
            m = tail(tile_fn({c: r[...] for c, r in zip(cols, in_refs)}))

            @pl.when(pl.program_id(0) == 0)
            def _():
                out_ref[...] = jnp.zeros((1, LANES), jnp.int32)

            out_ref[...] = out_ref[...] + jnp.sum(
                m.astype(jnp.int32), axis=0, dtype=jnp.int32, keepdims=True
            )

        partials = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=_in_specs,
            out_specs=pl.BlockSpec((1, LANES), lambda i: (_zero(), _zero())),
            out_shape=jax.ShapeDtypeStruct((1, LANES), jnp.int32),
            interpret=interpret,
        )(*mats)
        # final 128-way fold runs in XLA outside the kernel
        return jnp.sum(partials, dtype=jnp.int32)

    def mask_fn(coldict):
        n, grid, pad, mats = _prep(coldict)
        tail = _valid_mask(n)

        def kernel(*refs):
            *in_refs, out_ref = refs
            m = tail(tile_fn({c: r[...] for c, r in zip(cols, in_refs)}))
            out_ref[...] = m.astype(jnp.int8)

        m = pl.pallas_call(
            kernel,
            grid=(grid,),
            in_specs=_in_specs,
            out_specs=pl.BlockSpec((br, LANES), lambda i: (i, _zero())),
            out_shape=jax.ShapeDtypeStruct((grid * br, LANES), jnp.int8),
            interpret=interpret,
        )(*mats)
        return m.reshape(-1)[:n].astype(bool)

    return count_fn, mask_fn, cols
