"""Windowed SLO engine + flight recorder for the serving path.

Ref role: the operability layer GeoMesa ships as stats sketches and
audited query logs (PAPER.md [UNVERIFIED - empty reference mount]),
re-shaped into the SRE vocabulary a millions-of-users service is run
by: explicit latency objectives, error budgets, multi-window burn
rates, and an automatic postmortem bundle when a budget starts burning.

- **SLO definitions** come from conf — one per priority lane,
  ``slo.<name>.{objective,threshold.ms,window.s}`` with the lane names
  fixed by the :data:`SLO_NAMES` registry (lint rule GT009). A request
  is GOOD when it answers under its lane's latency threshold without a
  5xx; the error budget is ``1 - objective``.

- **Windowed tracking.** Latency observations land in
  :class:`WindowedHistogram` rings — time-rotated slots of the metrics
  histogram bucket layout, so the engine can answer "p50/p99/p999 over
  the last window" per endpoint/lane, not just since process start.
  Burn rate over a window = (bad fraction) / (error budget); the engine
  computes the classic fast (``slo.burn.fast.s``, default 5m) and slow
  (the SLO's own window, default 1h) pair. ``burning`` means BOTH
  windows exceed 1.0 — budget is being consumed faster than it accrues
  and has been for long enough to matter.

- **Exposure.** ``/stats/slo`` (the full document), ``/readyz``
  (burning SLOs appear as degraded detail — a burning instance still
  serves), and ``geomesa_slo_*`` metrics whose latency histogram
  buckets carry TRACE-ID EXEMPLARS: the p99 bucket on ``/metrics``
  names an actual captured trace in ``/debug/traces``.

- **Flight recorder.** When the fast-window burn crosses
  ``slo.flightrec.burn``, or a resilience circuit breaker opens, the
  :class:`FlightRecorder` snapshots a postmortem bundle — recent
  traces, the metrics exposition, the SLO/ledger/breaker state and any
  registered provider snapshots (sched/store/mesh) — atomically into
  ``<root>/_flightrec/<stamp>-<reason>/`` (tmp dir + rename), with
  bounded retention (``slo.flightrec.keep``) and per-reason rate
  limiting (``slo.flightrec.interval.s``). Reasons come from the
  :data:`FLIGHT_REASONS` registry (GT009).

Everything is gated by ``slo.enabled`` and built to stay off the hot
path: one ring update per request, burn math on small integer arrays,
and bundle writes only on (rate-limited) trigger events.
"""

from __future__ import annotations

import json
import os
import time
from bisect import bisect_left
from contextlib import contextmanager
from dataclasses import dataclass

from geomesa_tpu.locking import checked_lock

__all__ = [
    "SLO_NAMES",
    "FLIGHT_REASONS",
    "SloDef",
    "SloEngine",
    "WindowedHistogram",
    "FlightRecorder",
    "ENGINE",
    "FLIGHTREC",
    "enabled",
    "on_breaker_open",
    "slo_def",
    "slo_for_lane",
]

#: the SLO name registry (GT009): one SLO per scheduler priority lane.
#: Adding an SLO = a name here + its three conf keys in conf._DEFS.
#: ``ingest`` (the streaming-append lane) gets its own budget: sub-ms
#: appends at volume would otherwise dilute the interactive good-ratio
#: and mask a real latency breach from the burn-rate alerts.
SLO_NAMES = ("interactive", "batch", "ingest")

#: the flight-recorder reason registry (GT009): bundle directory names
#: and the geomesa_flightrec_bundles_total metric label both come from
#: here, so reasons stay a bounded, greppable enum
FLIGHT_REASONS = (
    "burn-rate", "breaker-open", "manual", "ingest-stall",
    "replica-failover", "replica-demote", "replica-reprovision",
    "pubsub-rearm",
)

#: windowed-histogram bucket bounds (seconds) — finer than the metrics
#: default so p999 at serving latencies is meaningful
WINDOW_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 0.75,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

#: ring geometry: the slow window divides into this many slots (60 =>
#: 60s slots for the default 1h window; the 5m fast window then spans
#: an exact 5 slots)
_SLOTS = 60

#: bounded endpoint/lane key space for the windowed histograms
_MAX_SERIES = 32


def enabled() -> bool:
    from geomesa_tpu.conf import sys_prop

    return bool(sys_prop("slo.enabled"))


@dataclass(frozen=True)
class SloDef:
    """One SLO: ``objective`` fraction of requests under
    ``threshold_ms`` over ``window_s`` (the slow burn window)."""

    name: str
    objective: float
    threshold_ms: float
    window_s: float

    @property
    def budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


#: name -> its conf keys (all literals: the GT008 registry covers them)
_SLO_KEYS = {
    "interactive": (
        "slo.interactive.objective",
        "slo.interactive.threshold.ms",
        "slo.interactive.window.s",
    ),
    "batch": (
        "slo.batch.objective",
        "slo.batch.threshold.ms",
        "slo.batch.window.s",
    ),
    "ingest": (
        "slo.ingest.objective",
        "slo.ingest.threshold.ms",
        "slo.ingest.window.s",
    ),
}


def slo_def(name: str) -> SloDef:
    """Resolve one registered SLO from conf (GT009 validates literal
    names against :data:`SLO_NAMES`)."""
    from geomesa_tpu.conf import sys_prop

    keys = _SLO_KEYS[name]
    return SloDef(
        name=name,
        objective=float(sys_prop(keys[0])),
        threshold_ms=float(sys_prop(keys[1])),
        window_s=float(sys_prop(keys[2])),
    )


def slo_for_lane(lane: str) -> SloDef:
    """The SLO governing a scheduler lane (unknown/empty lanes are held
    to the interactive objective — the strict default)."""
    return slo_def(lane if lane in SLO_NAMES else "interactive")


class WindowedHistogram:
    """Time-rotated ring of histogram slots: each slot covers
    ``slot_s`` seconds and holds bucket counts, sum, n and the good/bad
    split. Reading merges the slots inside the asked-for window, so
    percentiles and burn rates reflect the LAST window, not process
    lifetime. ``clock`` is injectable (monotonic seconds) for tests."""

    def __init__(
        self, window_s: float, buckets=WINDOW_BUCKETS,
        slots: int = _SLOTS, clock=time.monotonic,
    ):
        self.window_s = max(float(window_s), 1.0)
        self.slot_s = self.window_s / max(int(slots), 1)
        self.buckets = tuple(buckets)
        self.clock = clock
        n = max(int(slots), 1)
        self._n_slots = n
        # parallel arrays, one entry per ring position
        self._idx = [-1] * n  # absolute slot index occupying the pos
        self._counts = [[0] * (len(self.buckets) + 1) for _ in range(n)]
        self._sum = [0.0] * n
        self._n = [0] * n
        self._bad = [0] * n

    def _pos(self, idx: int) -> int:
        return idx % self._n_slots

    def _slot(self, now: float) -> int:
        return int(now / self.slot_s)

    def observe(self, v: float, bad: bool = False) -> None:
        idx = self._slot(self.clock())
        pos = self._pos(idx)
        if self._idx[pos] != idx:  # ring wrapped: this slot is stale
            self._idx[pos] = idx
            self._counts[pos] = [0] * (len(self.buckets) + 1)
            self._sum[pos] = 0.0
            self._n[pos] = 0
            self._bad[pos] = 0
        self._counts[pos][bisect_left(self.buckets, v)] += 1
        self._sum[pos] += v
        self._n[pos] += 1
        if bad:
            self._bad[pos] += 1

    def merged(self, window_s: "float | None" = None) -> dict:
        """Counts/sum/n/bad merged over the slots inside ``window_s``
        (default: the full ring window), stale slots excluded."""
        w = self.window_s if window_s is None else float(window_s)
        now_idx = self._slot(self.clock())
        k = max(int(round(w / self.slot_s)), 1)
        lo = now_idx - k  # slots (lo, now_idx] are inside the window
        counts = [0] * (len(self.buckets) + 1)
        total = 0.0
        n = 0
        bad = 0
        for pos in range(self._n_slots):
            idx = self._idx[pos]
            if idx <= lo or idx > now_idx:
                continue
            c = self._counts[pos]
            for i in range(len(counts)):
                counts[i] += c[i]
            total += self._sum[pos]
            n += self._n[pos]
            bad += self._bad[pos]
        return {"counts": counts, "sum": total, "n": n, "bad": bad}

    def quantile_ms(
        self, q: float, window_s: "float | None" = None
    ) -> "float | None":
        """Bucket-upper-bound quantile over the window (same estimator
        as a Prometheus ``histogram_quantile``), or None with no data."""
        m = self.merged(window_s)
        n = m["n"]
        if n <= 0:
            return None
        rank = q * n
        cum = 0
        for i, c in enumerate(m["counts"]):
            cum += c
            if cum >= rank and c:
                bound = (
                    self.buckets[i]
                    if i < len(self.buckets)
                    else max(self.buckets[-1], m["sum"] / n)
                )
                return round(bound * 1e3, 3)
        return round(self.buckets[-1] * 1e3, 3)


class SloEngine:
    """Process-wide SLO tracker: per-endpoint/lane windowed latency
    histograms, per-SLO good/bad rings, multi-window burn rates, and
    the burn-triggered flight-recorder hook. The module global
    :data:`ENGINE` is the serving one; tests build their own with a
    fake clock."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._lock = checked_lock("slo.engine")
        self._series: dict = {}  # (endpoint, lane) -> WindowedHistogram
        self._lanes: dict = {}  # slo name -> WindowedHistogram

    def _series_for(self, endpoint: str, lane: str, window_s: float):
        key = (endpoint, lane)
        h = self._series.get(key)
        if h is None:
            if len(self._series) >= _MAX_SERIES:
                key = ("other", lane)
                h = self._series.get(key)
            if h is None:
                h = self._series[key] = WindowedHistogram(
                    window_s, clock=self.clock
                )
        return h

    def _lane_for(self, d: SloDef):
        h = self._lanes.get(d.name)
        if h is None:
            h = self._lanes[d.name] = WindowedHistogram(
                d.window_s, clock=self.clock
            )
        return h

    def fast_window_s(self, d: SloDef) -> float:
        from geomesa_tpu.conf import sys_prop

        return min(float(sys_prop("slo.burn.fast.s")), d.window_s)

    def observe(
        self, endpoint: str, lane: str, dur_s: float,
        error: bool = False, trace_id: str = "",
    ) -> None:
        """Record one finished request against its lane's SLO. Updates
        the windowed rings, the exemplar-carrying metrics, and — when
        the fast-window burn crosses ``slo.flightrec.burn`` — triggers
        the flight recorder (rate-limited inside)."""
        if not enabled():
            return
        d = slo_for_lane(lane)
        # label discipline: the lane label is the RESOLVED SLO name
        # (bounded by SLO_NAMES — a client-supplied ?lane= novelty must
        # not mint metric series or ring keys), and the endpoint is
        # clamped by the server to its known endpoint set
        lane = d.name
        bad = bool(error) or dur_s * 1e3 > d.threshold_ms
        with self._lock:
            self._series_for(endpoint, lane, d.window_s).observe(
                dur_s, bad
            )
            self._lane_for(d).observe(dur_s, bad)
        from geomesa_tpu import metrics

        metrics.slo_latency.observe(
            dur_s,
            exemplar={"trace_id": trace_id} if trace_id else None,
            endpoint=endpoint, lane=lane,
        )
        metrics.slo_requests.inc(slo=d.name)
        if bad:
            metrics.slo_bad.inc(slo=d.name)
        burn_fast = self.burn(d, self.fast_window_s(d))
        metrics.slo_burn.set(burn_fast, slo=d.name, window="fast")
        from geomesa_tpu.conf import sys_prop

        trip = float(sys_prop("slo.flightrec.burn"))
        if trip > 0 and burn_fast >= trip:
            FLIGHTREC.trigger(
                "burn-rate",
                detail={
                    "slo": d.name,
                    "burn_fast": round(burn_fast, 3),
                    "threshold": trip,
                    "objective": d.objective,
                    "threshold_ms": d.threshold_ms,
                },
            )

    def burn(self, d: SloDef, window_s: float) -> float:
        """Burn rate over ``window_s``: observed bad fraction over the
        error budget. 0 with no traffic (no news is good news)."""
        with self._lock:
            h = self._lanes.get(d.name)
            m = h.merged(window_s) if h is not None else None
        if not m or m["n"] <= 0:
            return 0.0
        return (m["bad"] / m["n"]) / d.budget

    def burning(self) -> "list[str]":
        """SLO names burning on BOTH windows (the /readyz detail)."""
        out = []
        for name in SLO_NAMES:
            d = slo_def(name)
            if (
                self.burn(d, self.fast_window_s(d)) > 1.0
                and self.burn(d, d.window_s) > 1.0
            ):
                out.append(name)
        return out

    def snapshot(self) -> dict:
        """The ``/stats/slo`` document."""
        doc: dict = {"enabled": enabled(), "slos": {}, "series": {}}
        if not enabled():
            return doc
        from geomesa_tpu import metrics

        for name in SLO_NAMES:
            d = slo_def(name)
            fast_s = self.fast_window_s(d)
            burn_fast = self.burn(d, fast_s)
            burn_slow = self.burn(d, d.window_s)
            metrics.slo_burn.set(burn_fast, slo=name, window="fast")
            metrics.slo_burn.set(burn_slow, slo=name, window="slow")
            with self._lock:
                h = self._lanes.get(name)
                m = h.merged(d.window_s) if h is not None else None
            doc["slos"][name] = {
                "objective": d.objective,
                "threshold_ms": d.threshold_ms,
                "window_s": d.window_s,
                "requests": m["n"] if m else 0,
                "bad": m["bad"] if m else 0,
                "burn": {
                    "fast": {"window_s": fast_s, "rate": round(burn_fast, 4)},
                    "slow": {
                        "window_s": d.window_s, "rate": round(burn_slow, 4)
                    },
                },
                "burning": burn_fast > 1.0 and burn_slow > 1.0,
            }
        # ring reads happen UNDER the engine lock: observe() mutates
        # the same slot arrays concurrently and a torn read could pair
        # one slot's counts with another's totals
        with self._lock:
            for (endpoint, lane), h in sorted(self._series.items()):
                m = h.merged()
                doc["series"][f"{endpoint}|{lane}"] = {
                    "requests": m["n"],
                    "bad": m["bad"],
                    "p50_ms": h.quantile_ms(0.5),
                    "p99_ms": h.quantile_ms(0.99),
                    "p999_ms": h.quantile_ms(0.999),
                }
        return doc

    def reset(self) -> None:
        with self._lock:
            self._series.clear()
            self._lanes.clear()


# -- flight recorder --------------------------------------------------------


class FlightRecorder:
    """Postmortem bundle writer. Disabled until :meth:`configure` gives
    it a directory (make_server wires ``<store root>/_flightrec``);
    ``providers`` maps bundle file stems to zero-arg snapshot callables
    the server registers (sched/store/mesh stats)."""

    def __init__(self):
        self._lock = checked_lock("slo.flightrec")
        self.dir: "str | None" = None
        self.providers: dict = {}
        self._last: dict = {}  # reason -> last trigger monotonic
        self._seq = 0
        self.bundles = 0  # lifetime bundles written (tests/stats)

    def configure(self, directory: "str | None", providers=None) -> None:
        with self._lock:
            self.dir = directory
            if providers:
                self.providers.update(providers)

    def _interval_s(self) -> float:
        from geomesa_tpu.conf import sys_prop

        return float(sys_prop("slo.flightrec.interval.s"))

    def _keep(self) -> int:
        from geomesa_tpu.conf import sys_prop

        return max(int(sys_prop("slo.flightrec.keep")), 1)

    def trigger(self, reason: str, detail=None) -> "str | None":
        """Snapshot a bundle for ``reason`` (a :data:`FLIGHT_REASONS`
        name — GT009 checks call-site literals; unknown reasons are
        recorded as ``manual``). Returns the bundle path, or None when
        disabled / rate-limited. Never raises: the recorder must not
        break the serving path it observes."""
        if reason not in FLIGHT_REASONS:
            detail = {"requested_reason": reason, "detail": detail}
            reason = "manual"
        with self._lock:
            if self.dir is None or not enabled():
                return None
            now = time.monotonic()
            last = self._last.get(reason)
            if last is not None and now - last < self._interval_s():
                return None
            self._last[reason] = now
            self._seq += 1
            seq = self._seq
            directory = self.dir
            providers = dict(self.providers)
        try:
            return self._write_bundle(directory, reason, detail, seq,
                                      providers)
        except Exception:  # pragma: no cover - never break serving
            return None

    def _write_bundle(
        self, directory: str, reason: str, detail, seq: int, providers
    ) -> str:
        from geomesa_tpu import metrics, resilience
        from geomesa_tpu.ledger import LEDGER
        from geomesa_tpu.metrics import REGISTRY
        from geomesa_tpu.tracing import TRACER

        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        name = f"{stamp}-{seq:04d}-{reason}"
        tmp = os.path.join(directory, f".tmp-{os.getpid()}-{seq}")
        final = os.path.join(directory, name)
        os.makedirs(tmp, exist_ok=True)

        def dump(stem: str, doc) -> None:
            with open(os.path.join(tmp, stem), "w") as fh:
                if isinstance(doc, str):
                    fh.write(doc)
                else:
                    json.dump(doc, fh, indent=1, default=str)

        dump("reason.json", {
            "reason": reason,
            "detail": detail,
            # lint: disable=GT003(epoch timestamp persisted into the bundle record)
            "ts": round(time.time(), 3),
            "pid": os.getpid(),
        })
        recent = TRACER.recent(50)
        full = [
            t.to_dict()
            for t in (TRACER.get(s["trace_id"]) for s in recent[:10])
            if t is not None
        ]
        dump("traces.json", {"recent": recent, "full": full})
        dump("metrics.prom", REGISTRY.prometheus_text())
        dump("slo.json", ENGINE.snapshot())
        dump("ledger.json", LEDGER.snapshot())
        dump("breakers.json", resilience.snapshot())
        for stem, fn in providers.items():
            try:
                dump(f"{stem}.json", fn())
            except Exception:  # a dead provider must not kill the bundle
                dump(f"{stem}.json", {"error": "provider failed"})
        os.rename(tmp, final)  # atomic publish: readers never see a half-bundle
        with self._lock:
            self.bundles += 1
        metrics.flightrec_bundles.inc(reason=reason)
        self._prune(directory)
        return final

    def _prune(self, directory: str) -> None:
        """Bounded retention: keep the newest ``slo.flightrec.keep``
        bundles (name-sorted — stamps make names chronological)."""
        import shutil

        keep = self._keep()
        try:
            entries = sorted(
                e for e in os.listdir(directory)
                if not e.startswith(".tmp-")
                and os.path.isdir(os.path.join(directory, e))
            )
        except OSError:
            return
        for stale in entries[:-keep] if len(entries) > keep else []:
            shutil.rmtree(os.path.join(directory, stale),
                          ignore_errors=True)

    def bundle_names(self) -> "list[str]":
        with self._lock:
            directory = self.dir
        if not directory:
            return []
        try:
            return sorted(
                e for e in os.listdir(directory)
                if not e.startswith(".tmp-")
            )
        except OSError:
            return []

    def reset(self) -> None:
        with self._lock:
            self.dir = None
            self.providers.clear()
            self._last.clear()
            self._seq = 0
            self.bundles = 0


ENGINE = SloEngine()
FLIGHTREC = FlightRecorder()


def on_breaker_open(domain: str) -> None:
    """Resilience hook: a circuit breaker opened — snapshot a bundle
    naming the domain (called OUTSIDE the breaker lock; rate limiting
    and the enabled/dir gates live in :meth:`FlightRecorder.trigger`)."""
    FLIGHTREC.trigger("breaker-open", detail={"domain": domain})


@contextmanager
def fresh_engine(clock=time.monotonic):
    """Swap a fresh :class:`SloEngine` in as the module global for the
    with-body (tests: fake clocks without touching serving state)."""
    global ENGINE
    prev = ENGINE
    ENGINE = SloEngine(clock=clock)
    try:
        yield ENGINE
    finally:
        ENGINE = prev
