"""ctypes bindings for the native host library (native/zorder.cpp).

Loads ``native/build/libgeomesa_tpu.so``, compiling it on first use when a
C++ toolchain is available (``make -C native``). Every entry point has a
pure-Python/NumPy fallback with identical semantics (the Python versions
are the oracle; tests assert bit-identical outputs), so the package works
without the toolchain -- just slower planning.

Native entry points:
- bulk Morton encode/decode (2D/3D)
- fused quantize+encode z3 keys (the ingest hot loop)
- ``zranges`` litmax/bigmin decomposition (the query-planning hot loop)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_PATH = os.path.join(_LIB_DIR, "build", "libgeomesa_tpu.so")

_lock = threading.Lock()
_lib = None
_tried = False

_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _LIB_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def get_lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not os.path.exists(_LIB_PATH) and os.path.exists(
            os.path.join(_LIB_DIR, "zorder.cpp")
        ):
            _build()
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.gm_encode_2d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p]
        lib.gm_decode_2d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p]
        lib.gm_encode_3d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p, _u64p]
        lib.gm_decode_3d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p, _u64p]
        lib.gm_z3_index.argtypes = [
            ctypes.c_int64,
            _f64p,
            _f64p,
            _f64p,
            ctypes.c_double,
            _u64p,
        ]
        lib.gm_zranges.argtypes = [
            _u64p,
            _u64p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_int,
            _u64p,
            _u64p,
            _u8p,
            ctypes.c_int64,
        ]
        lib.gm_zranges.restype = ctypes.c_int64
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def enabled(use_native: bool = True) -> bool:
    """Shared gate: native lib built AND not disabled via
    GEOMESA_TPU_NO_NATIVE AND the caller's use_native flag."""
    return (
        use_native
        and not os.environ.get("GEOMESA_TPU_NO_NATIVE")
        and available()
    )


def encode_3d(x: np.ndarray, y: np.ndarray, t: np.ndarray) -> "np.ndarray | None":
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.uint64)
    y = np.ascontiguousarray(y, dtype=np.uint64)
    t = np.ascontiguousarray(t, dtype=np.uint64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.gm_encode_3d(len(x), x, y, t, out)
    return out


def z3_index(x: np.ndarray, y: np.ndarray, t: np.ndarray, t_max: float) -> "np.ndarray | None":
    """Fused quantize+encode (lon, lat, offset) -> z3, precision 21."""
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    t = np.ascontiguousarray(t, dtype=np.float64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.gm_z3_index(len(x), x, y, t, float(t_max), out)
    return out


def zranges_native(qlo, qhi, bits_per_dim, max_ranges, max_bits=-1):
    """Native range decomposition; returns list[IndexRange] or None."""
    lib = get_lib()
    if lib is None:
        return None
    from geomesa_tpu.curves.zranges import IndexRange

    dims = len(qlo)
    qlo_a = np.ascontiguousarray(np.asarray(qlo, dtype=np.uint64))
    qhi_a = np.ascontiguousarray(np.asarray(qhi, dtype=np.uint64))
    # gm_zranges merges down to <= max_ranges before writing, so this
    # capacity is never exceeded
    cap = max(int(max_ranges) * 2 + 16, 64)
    out_lo = np.empty(cap, dtype=np.uint64)
    out_hi = np.empty(cap, dtype=np.uint64)
    out_c = np.empty(cap, dtype=np.uint8)
    n = lib.gm_zranges(
        qlo_a, qhi_a, dims, bits_per_dim, max_ranges, max_bits,
        out_lo, out_hi, out_c, cap,
    )
    if n < 0:
        return None
    return [
        IndexRange(int(out_lo[i]), int(out_hi[i]), bool(out_c[i]))
        for i in range(n)
    ]
