"""ctypes bindings for the native host library (native/zorder.cpp).

Loads ``native/build/libgeomesa_tpu.so``, compiling it on first use when a
C++ toolchain is available (``make -C native``). Every entry point has a
pure-Python/NumPy fallback with identical semantics (the Python versions
are the oracle; tests assert bit-identical outputs), so the package works
without the toolchain -- just slower planning.

Native entry points:
- bulk Morton encode/decode (2D/3D)
- fused quantize+encode z3 keys (the ingest hot loop)
- ``zranges`` litmax/bigmin decomposition (the query-planning hot loop)
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

from geomesa_tpu.locking import checked_lock

_LIB_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_LIB_PATH = os.path.join(_LIB_DIR, "build", "libgeomesa_tpu.so")

# one-time load/build serialization: holding across the (blocking)
# compile + dlopen is the point -- a second caller must wait, not race
_lock = checked_lock("native.load", blocking_ok=True)
_lib = None
_tried = False

_u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
_f64p = np.ctypeslib.ndpointer(np.float64, flags="C_CONTIGUOUS")
_u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", _LIB_DIR],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return os.path.exists(_LIB_PATH)
    except Exception:
        return False


def get_lib():
    """The loaded native library, or None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        sources = [
            os.path.join(_LIB_DIR, f)
            for f in os.listdir(_LIB_DIR)
            if f.endswith(".cpp")
        ] if os.path.isdir(_LIB_DIR) else []
        stale = os.path.exists(_LIB_PATH) and any(
            os.path.getmtime(s) > os.path.getmtime(_LIB_PATH) for s in sources
        )
        if (not os.path.exists(_LIB_PATH) or stale) and sources:
            _build()
        if not os.path.exists(_LIB_PATH):
            return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
        except OSError:
            return None
        lib.gm_encode_2d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p]
        lib.gm_decode_2d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p]
        lib.gm_encode_3d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p, _u64p]
        lib.gm_decode_3d.argtypes = [ctypes.c_int64, _u64p, _u64p, _u64p, _u64p]
        lib.gm_z3_index.argtypes = [
            ctypes.c_int64,
            _f64p,
            _f64p,
            _f64p,
            ctypes.c_double,
            _u64p,
        ]
        lib.gm_zranges.argtypes = [
            _u64p,
            _u64p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int64,
            ctypes.c_int,
            _u64p,
            _u64p,
            _u8p,
            ctypes.c_int64,
        ]
        lib.gm_zranges.restype = ctypes.c_int64
        try:
            # newer symbols: a stale prebuilt .so may lack them -- degrade
            # to no-binser rather than poisoning every native entry point
            _i64p = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            _u32p = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
            lib.binser_headers.argtypes = [
                ctypes.c_char_p, _u64p, ctypes.c_int64, ctypes.c_int32,
                _u64p, _i64p, _u64p, _u32p, _u8p,
            ]
            lib.binser_headers.restype = ctypes.c_int
            lib.binser_column.argtypes = [
                ctypes.c_char_p, _u64p, _u64p, ctypes.c_int64,
                ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
                ctypes.c_void_p, _u64p, _u32p, _u8p,
            ]
            lib.binser_column.restype = ctypes.c_int
            lib._has_binser = True
        except AttributeError:
            lib._has_binser = False
        try:
            _i64p2 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.gm_xz_index.argtypes = [
                ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
                _f64p, _f64p, _i64p2,
            ]
            lib._has_xz = True
        except AttributeError:  # stale prebuilt .so without the symbol
            lib._has_xz = False
        try:
            _u32p2 = np.ctypeslib.ndpointer(np.uint32, flags="C_CONTIGUOUS")
            _i64p3 = np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS")
            lib.gm_radix_argsort.argtypes = [
                ctypes.c_int64, ctypes.c_int32, _u32p2, _i64p3,
            ]
            lib._has_sort = True
        except AttributeError:  # stale prebuilt .so without the symbol
            lib._has_sort = False
        _lib = lib
        return _lib


def available() -> bool:
    return get_lib() is not None


def enabled(use_native: bool = True) -> bool:
    """Shared gate: native lib built AND not disabled via
    GEOMESA_TPU_NO_NATIVE AND the caller's use_native flag."""
    return (
        use_native
        and not os.environ.get("GEOMESA_TPU_NO_NATIVE")
        and available()
    )


def encode_3d(x: np.ndarray, y: np.ndarray, t: np.ndarray) -> "np.ndarray | None":
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.uint64)
    y = np.ascontiguousarray(y, dtype=np.uint64)
    t = np.ascontiguousarray(t, dtype=np.uint64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.gm_encode_3d(len(x), x, y, t, out)
    return out


def z3_index(x: np.ndarray, y: np.ndarray, t: np.ndarray, t_max: float) -> "np.ndarray | None":
    """Fused quantize+encode (lon, lat, offset) -> z3, precision 21."""
    lib = get_lib()
    if lib is None:
        return None
    x = np.ascontiguousarray(x, dtype=np.float64)
    y = np.ascontiguousarray(y, dtype=np.float64)
    t = np.ascontiguousarray(t, dtype=np.float64)
    out = np.empty(len(x), dtype=np.uint64)
    lib.gm_z3_index(len(x), x, y, t, float(t_max), out)
    return out


def xz_index(mins: np.ndarray, maxs: np.ndarray, g: int, dims: int) -> "np.ndarray | None":
    """Bulk XZ extent-curve encode: normalized (dims, n) boxes -> int64
    sequence codes; bit-identical to curves/xz.py's walk (the oracle)."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_xz", False):
        return None
    # gm_xz_index uses fixed 32-slot / 3-slot stack buffers: reject out-of
    # -contract parameters HERE (a public entry point must not rely on the
    # caller having gone through XZSFC validation)
    if dims not in (2, 3) or not (1 <= g <= 31):
        return None
    mins = np.ascontiguousarray(mins, dtype=np.float64)
    maxs = np.ascontiguousarray(maxs, dtype=np.float64)
    n = mins.shape[1]
    out = np.empty(n, dtype=np.int64)
    lib.gm_xz_index(n, np.int32(dims), np.int32(g), mins, maxs, out)
    return out


def _order_preserving_u32_lanes(col: np.ndarray) -> "list[np.ndarray] | None":
    """Map a key column to uint32 lanes whose lexicographic order equals
    the column's natural order (most-significant lane first), or None when
    the dtype has no such mapping (the caller falls back to lexsort).
    Signed ints bias by the sign bit; 64-bit types split into hi/lo."""
    dt = col.dtype
    if dt == np.uint32:
        return [col]
    if dt == np.int32:
        return [(col.view(np.uint32) ^ np.uint32(0x80000000))]
    if dt == np.uint64:
        return [
            (col >> np.uint64(32)).astype(np.uint32),
            (col & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        ]
    if dt == np.int64:
        u = col.view(np.uint64) ^ np.uint64(1 << 63)
        return [
            (u >> np.uint64(32)).astype(np.uint32),
            (u & np.uint64(0xFFFFFFFF)).astype(np.uint32),
        ]
    if dt in (np.int16, np.uint16, np.int8, np.uint8):
        wide = col.astype(np.int64)
        return _order_preserving_u32_lanes(wide)
    return None


def radix_argsort(cols: list) -> "np.ndarray | None":
    """Stable lexicographic argsort of integer key columns (first column
    most significant) via the native digit-wise LSD radix kernel; None
    when the library is unavailable or a dtype has no order-preserving
    uint32 mapping. Bit-identical to np.lexsort (the oracle)."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_sort", False):
        return None
    lanes: list = []
    for col in cols:
        got = _order_preserving_u32_lanes(np.asarray(col))
        if got is None:
            return None
        lanes.extend(got)
    n = len(lanes[0]) if lanes else 0
    if n == 0:
        return np.empty(0, dtype=np.int64)
    # write each lane straight into the lane-major matrix (a stack() of
    # the mapped lanes would pay one more full copy of the key data)
    mat = np.empty((len(lanes), n), dtype=np.uint32)
    for i, lane in enumerate(lanes):
        mat[i, :] = lane
    out = np.empty(n, dtype=np.int64)
    lib.gm_radix_argsort(n, np.int32(len(lanes)), mat, out)
    return out


def zranges_native(qlo, qhi, bits_per_dim, max_ranges, max_bits=-1):
    """Native range decomposition; returns list[IndexRange] or None."""
    lib = get_lib()
    if lib is None:
        return None
    from geomesa_tpu.curves.zranges import IndexRange

    dims = len(qlo)
    qlo_a = np.ascontiguousarray(np.asarray(qlo, dtype=np.uint64))
    qhi_a = np.ascontiguousarray(np.asarray(qhi, dtype=np.uint64))
    # gm_zranges merges down to <= max_ranges before writing, so this
    # capacity is never exceeded
    cap = max(int(max_ranges) * 2 + 16, 64)
    out_lo = np.empty(cap, dtype=np.uint64)
    out_hi = np.empty(cap, dtype=np.uint64)
    out_c = np.empty(cap, dtype=np.uint8)
    n = lib.gm_zranges(
        qlo_a, qhi_a, dims, bits_per_dim, max_ranges, max_bits,
        out_lo, out_hi, out_c, cap,
    )
    if n < 0:
        return None
    return [
        IndexRange(int(out_lo[i]), int(out_hi[i]), bool(out_c[i]))
        for i in range(n)
    ]


# -- binary feature row batch decode (native/binser.cpp) ---------------------

# attribute type -> (column code, numpy dtype); strings use span outputs
_BINSER_CODES = {
    "Integer": (0, np.int64),
    "Long": (0, np.int64),
    "Date": (0, np.int64),
    "Float": (1, np.float32),
    "Double": (2, np.float64),
    "Boolean": (3, np.uint8),
}


def binser_decode(sft, rows, want):
    """Decode value blobs columnar via the C++ pass.

    Returns ``(cols, fids, flags)`` where cols maps requested attribute
    names to numpy arrays (strings decoded from spans; None for columns
    the native path cannot decode -- non-point geometry, Bytes, or
    numeric columns containing nulls), fids is the id array, and flags
    per row carries bit1 = has user-data. Returns None when the native
    library is unavailable or a row is malformed (caller falls back)."""
    lib = get_lib()
    if lib is None or not getattr(lib, "_has_binser", False) or not rows:
        return None
    attrs = {a.name: (i, a) for i, a in enumerate(sft.attributes)}
    n = len(rows)
    n_attrs = len(sft.attributes)
    data = b"".join(rows)
    row_off = np.zeros(n + 1, dtype=np.uint64)
    np.cumsum([len(r) for r in rows], out=row_off[1:])
    payload_base = np.empty(n, dtype=np.uint64)
    fids_int = np.empty(n, dtype=np.int64)
    fid_off = np.empty(n, dtype=np.uint64)
    fid_len = np.empty(n, dtype=np.uint32)
    flags = np.empty(n, dtype=np.uint8)
    rc = lib.binser_headers(
        data, row_off, n, n_attrs, payload_base, fids_int, fid_off, fid_len,
        flags,
    )
    if rc != 0:
        return None
    if np.any(flags & 1):  # string fids: build from spans
        fids = np.empty(n, dtype=object)
        for i in range(n):
            if flags[i] & 1:
                o, l = int(fid_off[i]), int(fid_len[i])
                fids[i] = data[o : o + l].decode("utf-8")
            else:
                fids[i] = int(fids_int[i])
    else:
        fids = fids_int  # freshly allocated here; no aliasing to protect

    cols: dict = {}
    nulls = np.empty(n, dtype=np.uint8)
    str_off = np.empty(n, dtype=np.uint64)
    str_len = np.empty(n, dtype=np.uint32)

    def run(attr_i, code, out):
        ptr = out.ctypes.data_as(ctypes.c_void_p) if out is not None else None
        return lib.binser_column(
            data, row_off, payload_base, n, n_attrs, attr_i, code,
            ptr, str_off, str_len, nulls,
        )

    for name in want:
        attr_i, a = attrs[name]
        if a.is_point:
            out = np.empty((n, 2), dtype=np.float64)
            if run(attr_i, 4, out) != 0 or nulls.any():
                cols[name] = None
                continue
            cols[name] = out
        elif a.type_name in ("String", "UUID"):
            if run(attr_i, 5, None) != 0:
                cols[name] = None
                continue
            vals = np.empty(n, dtype=object)
            for i in range(n):
                if nulls[i]:
                    vals[i] = None
                else:
                    o, l = int(str_off[i]), int(str_len[i])
                    vals[i] = data[o : o + l].decode("utf-8")
            cols[name] = vals
        elif a.type_name in _BINSER_CODES:
            code, _ = _BINSER_CODES[a.type_name]
            out = np.zeros(
                n, dtype=np.int64 if code == 0 else _BINSER_CODES[a.type_name][1]
            )
            if run(attr_i, code, out) != 0 or nulls.any():
                cols[name] = None  # nulls: defer to the python decoder
                continue
            if a.type_name == "Integer":
                out = out.astype(np.int32)
            elif a.type_name == "Boolean":
                out = out.astype(bool)  # matches COLUMN_DTYPES['Boolean']
            cols[name] = out
        else:
            cols[name] = None  # geometry (non-point) / Bytes
    return cols, fids, flags
