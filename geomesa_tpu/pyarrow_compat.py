"""Workaround for a pyarrow native-init thread hazard.

Observed in this environment (pyarrow + glibc build): if pyarrow is
FIRST imported on a non-main thread (e.g. an HTTP handler serving an
Arrow response, or an ingest worker), its native initialization is
corrupted and a LATER parquet read from the main thread segfaults inside
``read_table``. Importing pyarrow from the spawning thread before any
worker threads start avoids it entirely.

Every component that spawns threads which may touch Arrow/Parquet calls
``preload_pyarrow()`` first (server, jobs, partitioned-log consumers,
parallel frame scans). Importing ``geomesa_tpu`` itself stays
side-effect free — the preload happens at thread-pool construction, not
package import.
"""

from __future__ import annotations


def preload_pyarrow() -> None:
    """Import pyarrow (and its parquet module) on the CALLING thread.
    Idempotent and cheap after the first call; a missing pyarrow is the
    caller's problem later, not here."""
    try:
        import pyarrow  # noqa: F401
        import pyarrow.parquet  # noqa: F401
    except ImportError:  # pragma: no cover - pyarrow is baked in
        pass
