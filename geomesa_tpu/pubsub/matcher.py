"""Fused batch×subscriptions matching on the ingest path.

The subscription side is encoded ONCE per registry generation: every
subscription's coarse predicate envelope is XZ-encoded into a PR 11
join layout (:func:`geomesa_tpu.join.build_envelope_layout`). Each
acked append batch then runs as ONE fused spatial join against that
layout — one launch regardless of how many subscriptions stand (the
anti-pattern this tier exists to avoid is the per-subscription filter
loop) — and the coarse pairs are refined by the exact predicates:

- bbox: the coarse envelope IS the (intersected) bbox, and envelope
  overlap is the exact BBOX semantics, so no residual is needed;
- dwithin: exact center-to-envelope distance residual;
- ECQL: :func:`geomesa_tpu.filter.compile.evaluate_host` — the host
  twin of the device path's ``join.engine.filter_gate`` (a gate needs
  a staged DeviceIndex; append batches are raw host columns);
- visibility: :func:`geomesa_tpu.security.filter_by_visibility` with
  the subscription's frozen auths — fail closed, same as reads.

Matching runs on the ingest lane when a scheduler is attached (it
shares the append path's budget); the engine itself gets ``sched=None``
so the join does not nest a second scheduled slice inside the lane.
"""

from __future__ import annotations

import time

import numpy as np

from geomesa_tpu import metrics
from geomesa_tpu.failpoints import fail_point
from geomesa_tpu.filter.compile import evaluate_host
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.join import JoinEngine, build_envelope_layout
from geomesa_tpu.sched import LANE_INGEST
from geomesa_tpu.security import filter_by_visibility


class SubscriptionMatcher:
    """Encode-once layout cache + fused match over it.

    Not internally locked: the hub serializes calls per type (records
    are processed in seq order under its reorder buffer), and the
    layout cache is a per-generation swap — a stale read just rebuilds.
    """

    def __init__(self, registry, sched=None) -> None:
        self.registry = registry
        self.sched = sched
        self._layouts: dict = {}  # type -> (gen, jidx|None, subs, empty_mask)
        self._filters: dict = {}  # cql text -> parsed ast (subs-bounded)
        self.launches = 0  # fused join launches — asserted 1/batch in tests

    def invalidate(self) -> None:
        """Drop every cached layout (promotion re-arm)."""
        self._layouts.clear()
        self._filters.clear()

    # -- layout ------------------------------------------------------------

    def _layout(self, type_name: str, precision: int):
        gen = self.registry.gen
        cached = self._layouts.get(type_name)
        if cached is not None and cached[0] == gen:
            return cached[1], cached[2], cached[3]
        subs = self.registry.for_type(type_name)
        if not subs:
            entry = (gen, None, (), None)
        else:
            envs = np.stack([s.envelope() for s in subs])
            # provably-empty predicates (disjoint bbox∩dwithin∩cql) stay
            # in the layout as degenerate boxes so row ids keep aligning
            # with ``subs``; the empty mask drops their pairs post-join
            empty = ~np.isfinite(envs).all(axis=1)
            if empty.any():
                envs = envs.copy()
                envs[empty] = (0.0, 0.0, 0.0, 0.0)
            jidx = build_envelope_layout(envs, precision=precision, gen=gen)
            entry = (gen, jidx, subs, empty if empty.any() else None)
        self._layouts[type_name] = entry
        metrics.pubsub_subscriptions.set(float(self.registry.count()))
        return entry[1], entry[2], entry[3]

    def _filter(self, cql: str):
        f = self._filters.get(cql)
        if f is None:
            f = parse_ecql(cql)
            if len(self._filters) > 4 * max(1, self.registry.count()):
                self._filters.clear()  # bound by live subscription count
            self._filters[cql] = f
        return f

    # -- match -------------------------------------------------------------

    def match(self, type_name: str, batch, sft) -> list:
        """Match one acked batch against every standing subscription of
        its type in a single fused join. Returns ``[(sub, rows), ...]``
        with ``rows`` the matched batch row indices (ascending), only
        for subscriptions with at least one surviving match."""
        fail_point("fail.sub.match")
        jidx, subs, empty = self._layout(type_name, sft.xz_precision)
        if jidx is None or not len(batch):
            return []
        t0 = time.perf_counter()
        geom = sft.geom_field
        if geom is not None and sft.descriptor(geom).is_point:
            x, y = batch.point_coords(geom)
            fenvs = np.stack([x, y, x, y], axis=1)
        elif geom is not None:
            fenvs = np.asarray(batch.bboxes(geom), dtype=np.float64)
        else:
            return []
        eng = JoinEngine(jidx=jidx, sched=None)
        if self.sched is not None:
            res = self.sched.run(
                fn=lambda: eng.join(fenvs), lane=LANE_INGEST, tenant="_system"
            )
        else:
            res = eng.join(fenvs)
        self.launches += 1
        out = []
        if len(res.rows):
            order = np.argsort(res.rows, kind="stable")
            srows = np.asarray(res.rows)[order]
            swins = np.asarray(res.wins)[order]
            starts = np.concatenate(
                ([0], np.flatnonzero(np.diff(srows)) + 1)
            )
            bounds = np.append(starts[1:], len(srows))
            for lo, hi in zip(starts, bounds):
                si = int(srows[lo])
                if empty is not None and empty[si]:
                    continue
                sub = subs[si]
                rows = np.sort(swins[lo:hi].astype(np.int64))
                rows = self._refine(sub, batch, rows, fenvs)
                if len(rows):
                    out.append((sub, rows))
        metrics.pubsub_match_batches.inc()
        metrics.pubsub_match_pairs.inc(float(sum(len(r) for _s, r in out)))
        metrics.pubsub_match_seconds.observe(time.perf_counter() - t0)
        return out

    def _refine(self, sub, batch, rows: np.ndarray, fenvs: np.ndarray):
        """Exact residuals over the coarse pairs of one subscription."""
        keep = np.ones(len(rows), dtype=bool)
        # visibility: fail closed — a feature without clearance never
        # reaches a subscriber, exactly like the read path
        vmask = filter_by_visibility(batch, sub.auths)
        if vmask is not None:
            keep &= np.asarray(vmask, dtype=bool)[rows]
        if sub.dwithin is not None and keep.any():
            cx, cy, dist = sub.dwithin
            fe = fenvs[rows]
            dx = np.maximum(np.maximum(fe[:, 0] - cx, cx - fe[:, 2]), 0.0)
            dy = np.maximum(np.maximum(fe[:, 1] - cy, cy - fe[:, 3]), 0.0)
            keep &= np.hypot(dx, dy) <= dist
        if sub.cql and keep.any():
            mask = evaluate_host(self._filter(sub.cql), batch)
            keep &= np.asarray(mask, dtype=bool)[rows]
        return rows[keep]
