"""Continuous queries: the geofence/alert push tier (ISSUE 16).

Standing subscriptions ("alert me when anything enters this bbox /
corridor / proximity") evaluated against streaming append traffic —
the FeatureListener scenario from the reference architecture rebuilt
on this repo's primitives:

- ``registry``: the subscription registry — bbox / attribute-filter /
  dwithin predicates per type, persisted in its own WAL under the
  store root and replicated through the existing WAL shipping
  machinery (the ``_pubsub`` pseudo-type on ``GET /wal/<type>``), so a
  promoted follower re-arms every subscription with no operator step.
- ``matcher``: subscription envelopes are XZ-encoded ONCE per registry
  generation into a PR 11 join layout
  (:func:`geomesa_tpu.join.build_envelope_layout`); every acked append
  batch then matches against ALL subscriptions as ONE fused
  batch×subscriptions spatial join on the ingest lane — never a
  per-subscription loop — with exact attribute/dwithin residuals and
  fail-closed visibility refining the emitted pairs.
- ``delivery``: long-lived chunked/SSE push streams in the negotiated
  result formats (geojson/arrow/bin). Every delivery cursor rides the
  data WAL seq: a reconnecting subscriber resumes exactly-once from
  its acked watermark — records below it replay from the WAL through
  the same fused matcher, live matches arrive above it, and the two
  paths dedupe on the seq watermark.
"""

from geomesa_tpu.pubsub.delivery import CursorGoneError, PubSubHub
from geomesa_tpu.pubsub.matcher import SubscriptionMatcher
from geomesa_tpu.pubsub.registry import (
    REGISTRY_SHIP_NAME,
    Subscription,
    SubscriptionRegistry,
)

__all__ = [
    "CursorGoneError",
    "PubSubHub",
    "REGISTRY_SHIP_NAME",
    "Subscription",
    "SubscriptionMatcher",
    "SubscriptionRegistry",
]
