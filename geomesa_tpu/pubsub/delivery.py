"""The push hub: live fan-out, WAL-cursor replay, exactly-once resume.

Delivery protocol (the tentpole invariant):

- Every alert carries the WAL seq of the append batch that produced
  it — the seq IS the delivery cursor.
- A connecting subscriber names its acked watermark (``from=<seq>``,
  or SSE ``Last-Event-ID``). The hub registers the live queue FIRST,
  then replays every WAL record above the watermark through the same
  fused matcher, then switches to the live queue, skipping any queued
  event at or below the replay high-water mark. Because the queue was
  armed before the replay scan started, a record is either seen by the
  scan (and deduped out of the queue) or enqueued live — never missed,
  never doubled.
- The live queue is bounded (``sub.queue.events``); a subscriber that
  cannot keep up is torn down (``end: overflow``) and resumes from its
  cursor — exactly-once survives because the cursor does.
- Disconnected cursors pin data-WAL compaction (via the stream's
  retention-floor hook) for at most ``sub.retain.s``; beyond that the
  records may compact away and a stale cursor gets ``410`` /
  :class:`CursorGoneError` — the one documented way to lose alerts.

Follower/leader symmetry: the hub runs on every replica — the seq
listener fires identically for leader appends and follower
``apply_replicated`` — so any replica can serve push streams, and a
promoted leader re-arms matching from the replicated registry
(:meth:`PubSubHub.note_promoted`) with no missed and no duplicate
alerts.

Replication commit gate: under ``replica.ack=replica`` the leader's
hub holds matched events (``_pending``) until the record's seq is at
or below the highest follower-applied position
(:meth:`Replicator.commit_floor` → :meth:`PubSubHub.commit_advanced`).
Without the gate a subscriber could ack a seq from the leader's
unreplicated tail; a failover then voids that tail and REASSIGNS the
seq, and the resume-from-cursor replay would silently skip the new
record — the one way to break exactly-once. Replay is bounded below
the lowest pending seq for the same reason.
"""

from __future__ import annotations

import json
import logging
import queue
import time
from collections import deque as _deque

from geomesa_tpu import ledger, metrics
from geomesa_tpu.conf import sys_prop
from geomesa_tpu.export import feature_collection
from geomesa_tpu.failpoints import fail_point
from geomesa_tpu.locking import checked_lock
from geomesa_tpu.pubsub.matcher import SubscriptionMatcher
from geomesa_tpu.pubsub.registry import Subscription, SubscriptionRegistry
from geomesa_tpu.results.columnar import with_extra_columns
from geomesa_tpu.results.stream import arrow_stream_chunks, bin_stream_chunks
from geomesa_tpu.slo import FLIGHTREC

log = logging.getLogger("geomesa_tpu.pubsub")


class CursorGoneError(Exception):
    """The resume cursor points below the compacted tail of the data
    WAL (the subscriber stayed away longer than ``sub.retain.s``).
    Maps to HTTP 410: the client must re-read and re-subscribe."""


class _SubConn:
    """One live push connection: a bounded event queue plus the
    delivered watermark the retention floor consults."""

    __slots__ = ("q", "dead", "watermark")

    def __init__(self, capacity: int, watermark: int) -> None:
        self.q: "queue.Queue" = queue.Queue(maxsize=max(1, capacity))
        self.dead = False
        self.watermark = int(watermark)

    def offer(self, event) -> None:
        if self.dead:
            return
        try:
            self.q.put_nowait(event)
        except queue.Full:
            # slow consumer: tear down rather than block the ingest
            # path or grow without bound — the cursor makes this safe
            self.dead = True
            metrics.pubsub_stream_overflows.inc()

    def poison(self) -> None:
        try:
            self.q.put_nowait(None)
        except queue.Full:
            pass  # a full queue wakes the consumer anyway


class PubSubHub:
    """Registry + matcher + delivery, wired into one StreamingStore.

    Locking: ``pubsub.hub`` guards the connection/cursor tables (never
    held across blocking work); ``pubsub.match`` is an ordering lock —
    it serializes matching so events enqueue in seq order per type,
    which the watermark dedupe depends on (order: match -> hub,
    hub -> registry; nothing acquires match inside either)."""

    def __init__(self, stream, sched=None) -> None:
        self.stream = stream
        self.sched = sched
        self.registry = SubscriptionRegistry(stream.store.root)
        self.matcher = SubscriptionMatcher(self.registry, sched=sched)
        self._lock = checked_lock("pubsub.hub")
        self._match_lock = checked_lock("pubsub.match", blocking_ok=True)
        self._conns: dict = {}  # sub_id -> [_SubConn, ...]
        self._cursors: dict = {}  # sub_id -> (watermark_seq, monotonic_t)
        self._stash: dict = {}  # type -> {seq: batch} reorder buffer
        self._last: dict = {}  # type -> highest contiguously matched seq
        self._closed = False
        self.matched_records = 0
        self.match_faults = 0
        self.rearms = 0
        #: replication commit gate (leader + ``replica.ack=replica``):
        #: ``callable(type_name) -> int | None`` giving the highest seq
        #: some follower has applied. When armed, a matched event whose
        #: seq is above the floor is HELD in ``_pending`` instead of
        #: fanned out — a live alert must never name a seq a failover
        #: could void and reassign. ``None`` = deliver immediately.
        self.commit_gate = None
        self._pending: dict = {}  # type -> deque[(seq, batch, matches)]
        self.commit_drops = 0
        # seed retention pins for subscriptions recovered from the
        # registry WAL (leader restart): never-connected subs pin at
        # their creation seq until sub.retain.s ages them out
        now = time.monotonic()
        for doc in self.registry.list():
            self._cursors[doc["id"]] = (int(doc["createdSeq"]), now)
        stream.add_seq_listener(self.on_record)
        stream.add_retention_floor(self.retention_floor)

    # -- subscription CRUD (leader-side; followers apply via replica) -------

    def subscribe(self, type_name: str, doc: dict, *, tenant, auths) -> dict:
        sft = self.stream.store.get_schema(type_name)  # KeyError -> 404
        wal = self.stream._ts(type_name).wal
        sub = Subscription.parse(
            type_name,
            doc,
            sft,
            tenant=tenant,
            auths=auths,
            created_seq=wal.next_seq - 1,
        )
        seq = self.registry.subscribe(sub)
        with self._lock:
            self._cursors[sub.sub_id] = (sub.created_seq, time.monotonic())
        return {
            "id": sub.sub_id,
            "type": type_name,
            "cursor": sub.created_seq,
            "registrySeq": seq,
        }

    def cancel(self, sub_id: str) -> bool:
        ok = self.registry.unsubscribe(sub_id)
        with self._lock:
            self._cursors.pop(sub_id, None)
            conns = list(self._conns.get(sub_id, ()))
        for c in conns:
            c.poison()  # their loops see the registry miss and end
        return ok

    # -- ingest-side matching (the stream's seq listener) --------------------

    def on_record(self, type_name: str, batch, seq: int) -> None:
        if self._closed:
            return
        with self._match_lock:
            ready = self._drain_in_order(type_name, batch, seq)
            for s, b in ready:
                try:
                    # lint: disable=GT002(the match lock's purpose is
                    # seq-ordered event dispatch; declared blocking_ok)
                    self._match_record(type_name, b, s)
                except Exception:  # lint: disable=GT011(reasoned swallow: a match fault must never un-ack the append; counted + logged, cursor replay re-derives the alerts)
                    # a match fault must never un-ack the append: the
                    # cursor replay path re-derives the missed alerts
                    self.match_faults += 1
                    log.warning(
                        "pubsub match fault on %s seq=%d", type_name, s,
                        exc_info=True,
                    )

    def _drain_in_order(self, type_name: str, batch, seq: int) -> list:
        """Contiguity reorder buffer: the seq listener fires outside the
        memtable lock, so two appends can notify swapped — stash until
        the predecessor arrives so queues fill in seq order per type."""
        last = self._last.get(type_name)
        if last is None:
            # first record seen this process: trust it as the tail (a
            # lower seq notified later — theoretical first-notify race —
            # just processes immediately below)
            self._last[type_name] = seq - 1
            last = seq - 1
        if seq <= last:
            return [(seq, batch)]
        stash = self._stash.setdefault(type_name, {})
        stash[seq] = batch
        ready = []
        while last + 1 in stash:
            last += 1
            ready.append((last, stash.pop(last)))
        self._last[type_name] = last
        if len(stash) > 64:
            # a hole that never fills (listener fault upstream) must not
            # pin batches forever: flush out of order and move the tail
            for s in sorted(stash):
                ready.append((s, stash.pop(s)))
            self._last[type_name] = max(last, ready[-1][0])
        return ready

    def _match_record(self, type_name: str, batch, seq: int) -> None:
        sft = self.stream.store.get_schema(type_name)
        matches = self.matcher.match(type_name, batch, sft)
        self.matched_records += 1
        if matches and ledger.enabled():
            for sub, rows in matches:
                cost = ledger.RequestCost(
                    tenant=sub.tenant,
                    endpoint="subscribe",
                    lane="ingest",
                    shape="push-match",
                )
                cost.status = 200
                cost.charge("sub_matches", float(len(rows)))
                ledger.LEDGER.record(cost)
        if not matches:
            return
        gate = self.commit_gate
        if gate is not None:
            floor = gate(type_name)
            with self._lock:
                dq = self._pending.get(type_name)
                if dq or (floor is not None and seq > floor):
                    # not yet replication-durable (or FIFO behind one
                    # that isn't): hold until the commit floor advances
                    if dq is None:
                        dq = self._pending.setdefault(type_name, _deque())
                    dq.append((seq, batch, matches))
                    cap = 4 * int(sys_prop("sub.queue.events"))
                    while len(dq) > cap:
                        # quorum dead and ingest still running: shed the
                        # OLDEST — it re-enters via cursor replay, which
                        # is bounded below the surviving pending head
                        dq.popleft()
                        self.commit_drops += 1
                    return
        self._deliver(seq, batch, matches)

    def _deliver(self, seq: int, batch, matches) -> None:
        with self._lock:
            for sub, rows in matches:
                for conn in self._conns.get(sub.sub_id, ()):
                    conn.offer((seq, batch, rows))

    def commit_advanced(self, type_name: "str | None" = None) -> None:
        """Replication-commit kick (the leader calls this whenever a
        follower's applied position advances): flush pending matched
        events that are now at or below the commit floor, in seq order.
        Serialized under the match lock so a flush and a fresh append
        can never interleave their enqueues out of order."""
        gate = self.commit_gate
        if gate is None or self._closed:
            return
        with self._match_lock:
            # lint: disable=GT002(seq-ordered dispatch lock; blocking_ok)
            with self._lock:
                types = (
                    [type_name] if type_name is not None
                    else list(self._pending)
                )
            for t in types:
                floor = gate(t)
                while True:
                    with self._lock:
                        dq = self._pending.get(t)
                        if not dq or (
                            floor is not None and dq[0][0] > floor
                        ):
                            break
                        seq, batch, matches = dq.popleft()
                        if not dq:
                            self._pending.pop(t, None)
                    self._deliver(seq, batch, matches)

    # -- delivery -----------------------------------------------------------

    def cursor_gone(self, type_name: str, from_seq: int) -> bool:
        """True when records above ``from_seq`` have been compacted out
        of the data WAL — the resume would silently skip them."""
        wal = self.stream._ts(type_name).wal
        first = wal.first_seq()
        if first >= 0:
            return from_seq + 1 < first
        return from_seq < wal.next_seq - 1

    def events(self, type_name: str, sub_id: str, from_seq: int,
               heartbeat_s: float):
        """Return a generator of ``("match", seq, matched_batch, rows)``
        / ``("heartbeat", watermark)`` / ``("end", reason)`` events,
        exactly-once above ``from_seq``. Validation is EAGER — KeyError
        (unknown subscription) and :class:`CursorGoneError` raise here,
        at call time, not at first iteration: the HTTP layer must still
        be able to answer 404/410 before any stream bytes go out."""
        sub = self.registry.get(sub_id)
        if sub is None or sub.type_name != type_name:
            raise KeyError("unknown subscription %r for %r" % (sub_id, type_name))
        sft = self.stream.store.get_schema(type_name)
        wal = self.stream._ts(type_name).wal
        if self.cursor_gone(type_name, from_seq):
            raise CursorGoneError(
                "cursor %d predates the compacted WAL tail of %r "
                "(retained at most sub.retain.s after disconnect)"
                % (from_seq, type_name)
            )
        return self._event_stream(
            type_name, sub_id, from_seq, heartbeat_s, sub, sft, wal
        )

    def _event_stream(self, type_name: str, sub_id: str, from_seq: int,
                      heartbeat_s: float, sub, sft, wal):
        """The generator half of :meth:`events`: owns the connection
        lifecycle (queue armed before the replay scan, cursor stamped on
        the way out)."""
        watermark = int(from_seq)
        conn = _SubConn(int(sys_prop("sub.queue.events")), watermark)
        with self._lock:
            self._conns.setdefault(sub_id, []).append(conn)
            self._cursors[sub_id] = (watermark, time.monotonic())
            # replay stops below the lowest commit-pending seq: records
            # at or above it are not replication-durable yet and reach
            # this (already armed) queue via the commit flush instead
            dq = self._pending.get(type_name)
            bound = (int(dq[0][0]) - 1) if dq else None
        try:
            # replay below the live tail (queue armed above, so records
            # land in exactly one of the two paths; dups dedupe on seq)
            for seq, payload in wal.read_from(watermark):
                if bound is not None and seq > bound:
                    break
                batch = self.stream._decode(type_name, payload)
                metrics.pubsub_replay_records.inc()
                rows = self._replay_match(sub, type_name, batch, sft)
                watermark = seq
                self._note_progress(sub_id, conn, watermark)
                if rows is not None and len(rows):
                    yield ("match", seq, batch.take(rows), rows)
            # live tail
            while True:
                if conn.dead:
                    yield ("end", "overflow")
                    return
                if self._closed:
                    yield ("end", "shutdown")
                    return
                if self.registry.get(sub_id) is None:
                    yield ("end", "cancelled")
                    return
                try:
                    ev = conn.q.get(timeout=max(0.05, heartbeat_s))
                except queue.Empty:
                    yield ("heartbeat", watermark)
                    continue
                if ev is None:
                    continue  # poison: re-check closed/cancelled above
                seq, batch, rows = ev
                if seq <= watermark:
                    continue  # the replay pass already covered this seq
                watermark = seq
                self._note_progress(sub_id, conn, watermark)
                yield ("match", seq, batch.take(rows), rows)
        finally:
            with self._lock:
                lst = self._conns.get(sub_id)
                if lst is not None and conn in lst:
                    lst.remove(conn)
                    if not lst:
                        self._conns.pop(sub_id, None)
                # the disconnect stamp starts the sub.retain.s clock
                self._cursors[sub_id] = (watermark, time.monotonic())

    def _replay_match(self, sub, type_name, batch, sft):
        """Replay matching is the SAME fused join (full layout, one
        launch per replayed batch), filtered to the resuming sub."""
        with self._match_lock:
            # lint: disable=GT002(seq-ordered dispatch lock; blocking_ok)
            matches = self.matcher.match(type_name, batch, sft)
        for s, rows in matches:
            if s.sub_id == sub.sub_id:
                return rows
        return None

    def _note_progress(self, sub_id, conn, watermark: int) -> None:
        conn.watermark = watermark
        with self._lock:
            self._cursors[sub_id] = (watermark, time.monotonic())

    # -- retention ----------------------------------------------------------

    def retention_floor(self, type_name: str):
        """Min delivery cursor across this type's subscribers: live
        connections pin at their delivered watermark; disconnected ones
        pin for at most ``sub.retain.s`` after their last progress."""
        retain_s = float(sys_prop("sub.retain.s"))
        now = time.monotonic()
        with self._lock:
            cursors = dict(self._cursors)
            conns = {sid: list(cs) for sid, cs in self._conns.items()}
        floor = None
        for sid, (seq, t) in cursors.items():
            sub = self.registry.get(sid)
            if sub is None or sub.type_name != type_name:
                continue
            live = conns.get(sid)
            if live:
                seq = min(c.watermark for c in live)
            elif now - t > retain_s:
                continue  # aged out: stop pinning compaction
            floor = seq if floor is None else min(floor, seq)
        return floor

    # -- failover -----------------------------------------------------------

    def note_promoted(self) -> None:
        """Re-arm after this replica's promotion: invalidate the layout
        cache (rebuilt from the replicated registry on the next acked
        batch) and pin retention for every known subscription so the
        new leader does not compact below a resuming cursor."""
        self.matcher.invalidate()
        now = time.monotonic()
        with self._lock:
            for doc in self.registry.list():
                if doc["id"] not in self._cursors:
                    self._cursors[doc["id"]] = (int(doc["createdSeq"]), now)
        self.rearms += 1
        metrics.pubsub_rearms.inc()
        FLIGHTREC.trigger(
            "pubsub-rearm",
            {"subscriptions": self.registry.count(), "gen": self.registry.gen},
        )

    # -- observability / lifecycle ------------------------------------------

    def stats(self) -> dict:
        """The /stats/pubsub document."""
        with self._lock:
            conns = {sid: list(cs) for sid, cs in self._conns.items()}
            cursors = dict(self._cursors)
            pending = sum(len(dq) for dq in self._pending.values())
        subs = []
        for doc in self.registry.list():
            sid = doc["id"]
            try:
                nxt = self.stream._ts(doc["type"]).wal.next_seq
            except KeyError:
                nxt = 0
            live = conns.get(sid, ())
            cur = cursors.get(sid, (doc["createdSeq"],))[0]
            if live:
                cur = min(c.watermark for c in live)
            subs.append({
                **doc,
                "connected": len(live),
                "cursor": int(cur),
                "lag": max(0, nxt - 1 - int(cur)),
            })
        return {
            "enabled": True,
            "registry": self.registry.stats(),
            "subscriptions": subs,
            "connections": sum(len(v) for v in conns.values()),
            "matched_records": self.matched_records,
            "match_faults": self.match_faults,
            "fused_launches": self.matcher.launches,
            "rearms": self.rearms,
            "commit_gated": self.commit_gate is not None,
            "commit_pending": pending,
            "commit_drops": self.commit_drops,
        }

    def close(self) -> None:
        with self._lock:
            if self._closed:
                return
            self._closed = True
            conns = [c for lst in self._conns.values() for c in lst]
        for c in conns:
            c.poison()
        self.stream.remove_seq_listener(self.on_record)
        self.stream.remove_retention_floor(self.retention_floor)
        self.registry.close()


# ---------------------------------------------------------------------------
# wire encodings (the negotiated result formats, push-shaped)
# ---------------------------------------------------------------------------

#: content type of the geojson push encoding (Server-Sent Events);
#: the full per-format table is results.PUSH_CONTENT_TYPES
SSE_CONTENT_TYPE = "text/event-stream"


def sse_chunks(events, type_name: str, sub_id: str):
    """GeoJSON push encoding: one SSE ``match`` event per matched batch
    (``id:`` = the WAL-seq cursor, ``data:`` = a FeatureCollection plus
    cursor fields), ``:keepalive`` comments on idle heartbeats. The
    preamble comment flushes headers before any match exists."""
    yield (":subscribed %s %s\nretry: 1000\n\n" % (type_name, sub_id)).encode()
    for ev in events:
        kind = ev[0]
        if kind == "heartbeat":
            metrics.pubsub_heartbeats.inc()
            yield b":keepalive\n\n"
        elif kind == "match":
            _kind, seq, batch, _rows = ev
            doc = feature_collection(batch)
            doc["seq"] = int(seq)
            doc["subscription"] = sub_id
            doc["featureType"] = type_name
            metrics.pubsub_events_delivered.inc()
            yield (
                "id: %d\nevent: match\ndata: %s\n\n"
                % (int(seq), json.dumps(doc, separators=(",", ":")))
            ).encode()
        else:  # ("end", reason)
            yield (
                "event: end\ndata: %s\n\n" % json.dumps({"reason": ev[1]})
            ).encode()
            return


def arrow_push_chunks(events, sft):
    """Arrow push encoding: one IPC stream; each matched batch becomes
    a record chunk with a ``match_seq`` column carrying the cursor.
    No in-band heartbeat bytes (idle Arrow streams stay silent — SSE is
    the keep-alive format; the socket reap exemption covers this)."""

    def _batches():
        for ev in events:
            if ev[0] != "match":
                continue
            _kind, seq, batch, _rows = ev
            metrics.pubsub_events_delivered.inc()
            yield with_extra_columns(
                batch, {"match_seq": [int(seq)] * len(batch)}
            )

    return arrow_stream_chunks(_batches())


def bin_push_chunks(events, track_attr: str):
    """BIN push encoding: matched batches as track records. The seq
    cursor has no in-band slot in the 16/24-byte records — resuming BIN
    subscribers reconnect from their last *acked* seq via ``from=``
    (documented in the README)."""

    def _batches():
        for ev in events:
            if ev[0] != "match":
                continue
            metrics.pubsub_events_delivered.inc()
            yield ev[2]

    return bin_stream_chunks(_batches(), track_attr)
