"""Standing-subscription registry with its own replicated WAL.

A subscription is a (type, predicate, tenant) triple: predicate = any
combination of a bbox, an ECQL attribute filter, and a dwithin
proximity circle. The registry persists every mutation as a JSON op
record in a dedicated :class:`~geomesa_tpu.store.wal.WriteAheadLog`
under ``<store.root>/_pubsub/wal`` — the same durability primitive the
data path uses — and that WAL ships to followers through the existing
``GET /wal/<type>`` machinery as the reserved pseudo-type
``_pubsub``. A promoted follower therefore already holds the full
registry and re-arms matching with no missed subscriptions.

The registry WAL is never truncated: its volume is bounded by
subscription churn (tiny JSON records), not by data traffic, and
keeping every op means a follower that fell arbitrarily far behind can
always catch up from ``from=next_seq`` — registry shipping can never
410.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import uuid
from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu.conf import sys_prop
from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.filter.extract import extract_geometries
from geomesa_tpu.locking import checked_lock
from geomesa_tpu.store.wal import WriteAheadLog

log = logging.getLogger("geomesa_tpu.pubsub")

#: Reserved type name the registry WAL ships under on ``GET /wal/<type>``.
#: Leading underscore keeps it out of the real schema namespace (stores
#: reject feature type names that are not identifiers).
REGISTRY_SHIP_NAME = "_pubsub"

_WORLD = (-180.0, -90.0, 180.0, 90.0)


@dataclass(frozen=True)
class Subscription:
    """One standing continuous query against a feature type."""

    sub_id: str
    type_name: str
    tenant: str = "anonymous"
    bbox: tuple | None = None  # (xmin, ymin, xmax, ymax) degrees
    cql: str = ""  # ECQL attribute/spatial residual ("" = none)
    dwithin: tuple | None = None  # (x, y, distance) planar degrees
    auths: tuple = ()  # visibility authorizations (fail closed)
    created_seq: int = -1  # data-WAL watermark when armed

    # -- predicate envelope -------------------------------------------------

    def envelope(self) -> np.ndarray:
        """The coarse (4,) search envelope: the intersection of every
        bounded predicate component. This is what gets XZ-encoded into
        the join layout; the exact predicates re-run as residuals."""
        x0, y0, x1, y1 = _WORLD
        if self.bbox is not None:
            bx0, by0, bx1, by1 = self.bbox
            x0, y0 = max(x0, bx0), max(y0, by0)
            x1, y1 = min(x1, bx1), min(y1, by1)
        if self.dwithin is not None:
            cx, cy, dist = self.dwithin
            x0, y0 = max(x0, cx - dist), max(y0, cy - dist)
            x1, y1 = min(x1, cx + dist), min(y1, cy + dist)
        if self.cql:
            env = _cql_envelope(self.cql)
            if env is not None:
                x0, y0 = max(x0, env[0]), max(y0, env[1])
                x1, y1 = min(x1, env[2]), min(y1, env[3])
        if x1 < x0 or y1 < y0:  # provably empty predicate
            x0 = y0 = x1 = y1 = float("nan")
        return np.asarray((x0, y0, x1, y1), dtype=np.float64)

    def parsed_filter(self) -> "ast.Filter | None":
        return parse_ecql(self.cql) if self.cql else None

    # -- wire form ----------------------------------------------------------

    def to_doc(self) -> dict:
        doc = {
            "id": self.sub_id,
            "type": self.type_name,
            "tenant": self.tenant,
            "auths": list(self.auths),
            "createdSeq": int(self.created_seq),
        }
        if self.bbox is not None:
            doc["bbox"] = list(self.bbox)
        if self.cql:
            doc["cql"] = self.cql
        if self.dwithin is not None:
            doc["dwithin"] = {
                "x": self.dwithin[0],
                "y": self.dwithin[1],
                "distance": self.dwithin[2],
            }
        return doc

    @staticmethod
    def from_doc(doc: dict) -> "Subscription":
        dw = doc.get("dwithin")
        return Subscription(
            sub_id=str(doc["id"]),
            type_name=str(doc["type"]),
            tenant=str(doc.get("tenant") or "anonymous"),
            bbox=tuple(float(v) for v in doc["bbox"]) if doc.get("bbox") else None,
            cql=str(doc.get("cql") or ""),
            dwithin=(
                (float(dw["x"]), float(dw["y"]), float(dw["distance"]))
                if dw
                else None
            ),
            auths=tuple(str(a) for a in doc.get("auths") or ()),
            created_seq=int(doc.get("createdSeq", -1)),
        )

    @staticmethod
    def parse(
        type_name: str,
        doc: dict,
        sft: SimpleFeatureType,
        *,
        tenant: str,
        auths,
        created_seq: int,
    ) -> "Subscription":
        """Validate a client subscription request body into a Subscription.
        Raises ValueError (-> 400) on a malformed or empty predicate."""
        if not isinstance(doc, dict):
            raise ValueError("subscription body must be a JSON object")
        bbox = doc.get("bbox")
        if bbox is not None:
            try:
                bbox = tuple(float(v) for v in bbox)
            except (TypeError, ValueError):
                raise ValueError("bbox must be [xmin, ymin, xmax, ymax]")
            if len(bbox) != 4 or bbox[0] > bbox[2] or bbox[1] > bbox[3]:
                raise ValueError("bbox must be [xmin, ymin, xmax, ymax]")
        cql = str(doc.get("cql") or doc.get("filter") or "")
        if cql:
            parse_ecql(cql)  # validate now; matcher re-parses from cache
        dw = doc.get("dwithin")
        if dw is not None:
            try:
                dw = (float(dw["x"]), float(dw["y"]), float(dw["distance"]))
            except (TypeError, KeyError, ValueError):
                raise ValueError("dwithin must be {x, y, distance}")
            if dw[2] < 0:
                raise ValueError("dwithin distance must be >= 0")
        if bbox is None and not cql and dw is None:
            raise ValueError(
                "subscription needs at least one predicate: bbox, cql or dwithin"
            )
        return Subscription(
            sub_id=uuid.uuid4().hex[:12],
            type_name=type_name,
            tenant=str(tenant or "anonymous"),
            bbox=bbox,
            cql=cql,
            dwithin=dw,
            auths=tuple(auths) if auths is not None else (),
            created_seq=int(created_seq),
        )


def _cql_envelope(cql: str) -> tuple | None:
    """Union envelope of the filter's spatial bounds, or None when the
    filter does not constrain geometry (attribute-only predicates)."""
    try:
        f = parse_ecql(cql)
    except ValueError:
        return None
    # the geometry attribute name differs per type; extract against every
    # spatial attr mentioned is overkill -- use the conventional wildcard
    # by probing the filter's own spatial nodes via extract on each attr
    attrs = _spatial_attrs(f)
    env = None
    for attr in attrs:
        bounds = extract_geometries(f, attr)
        if bounds.unbounded or not bounds.values:
            continue
        for e, _geom in bounds.values:
            box = (e.xmin, e.ymin, e.xmax, e.ymax)
            if env is None:
                env = box
            else:  # union across disjuncts/attrs stays conservative
                env = (
                    min(env[0], box[0]),
                    min(env[1], box[1]),
                    max(env[2], box[2]),
                    max(env[3], box[3]),
                )
    return env


def _spatial_attrs(f) -> set:
    out = set()
    if isinstance(f, (ast.BBox, ast.Intersects, ast.DWithin)):
        out.add(f.attr)
    for child in getattr(f, "children", ()) or ():
        out |= _spatial_attrs(child)
    inner = getattr(f, "child", None)
    if inner is not None:
        out |= _spatial_attrs(inner)
    return out


class SubscriptionRegistry:
    """Durable, replicated registry of standing subscriptions.

    Mutations append a JSON op record to the registry WAL BEFORE the
    in-memory tables change (same ack discipline as the data path);
    ``apply_replicated`` is the follower-side twin, idempotent on seq.
    ``gen`` bumps on every mutation — the matcher keys its encode-once
    join layout on it.
    """

    def __init__(self, root: str) -> None:
        self.root = root
        self._dir = os.path.join(root, REGISTRY_SHIP_NAME, "wal")
        self._wal = WriteAheadLog(self._dir)
        # WAL-append ordering is this lock's purpose (see store/stream.py)
        self._lock = checked_lock("pubsub.registry", blocking_ok=True)
        self._subs: dict = {}  # sub_id -> Subscription
        self._by_type: dict = {}  # type_name -> {sub_id: Subscription}
        self._gen = 0
        self._recover()

    # -- durability ---------------------------------------------------------

    def _recover(self) -> None:
        n = 0
        for _seq, payload in self._wal.replay():
            self._apply_op(payload)
            n += 1
        if n:
            log.info(
                "pubsub registry recovered %d ops -> %d subscriptions",
                n,
                len(self._subs),
            )

    def _apply_op(self, payload: bytes) -> None:
        try:
            op = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            log.warning("pubsub registry skipping undecodable op record")
            return
        kind = op.get("op")
        if kind == "subscribe":
            sub = Subscription.from_doc(op["sub"])
            self._subs[sub.sub_id] = sub
            self._by_type.setdefault(sub.type_name, {})[sub.sub_id] = sub
            self._gen += 1
        elif kind == "unsubscribe":
            sub = self._subs.pop(str(op.get("id")), None)
            if sub is not None:
                self._by_type.get(sub.type_name, {}).pop(sub.sub_id, None)
                self._gen += 1

    # -- leader mutations ---------------------------------------------------

    def subscribe(self, sub: Subscription) -> int:
        """Durably register ``sub``; returns the registry WAL seq."""
        payload = json.dumps({"op": "subscribe", "sub": sub.to_doc()}).encode()
        with self._lock:
            cap = int(sys_prop("sub.max.per.type"))
            if len(self._by_type.get(sub.type_name, ())) >= cap:
                raise ValueError(
                    "subscription cap reached for %r (sub.max.per.type=%d)"
                    % (sub.type_name, cap)
                )
            # lint: disable=GT002(registry WAL append ordering is this
            # lock's purpose; blocking_ok declared at construction)
            seq = self._wal.append(payload)
            self._apply_op(payload)
        return seq

    def unsubscribe(self, sub_id: str) -> bool:
        with self._lock:
            if sub_id not in self._subs:
                return False
            payload = json.dumps({"op": "unsubscribe", "id": sub_id}).encode()
            # lint: disable=GT002(registry WAL append ordering is this
            # lock's purpose; blocking_ok declared at construction)
            self._wal.append(payload)
            self._apply_op(payload)
        return True

    # -- follower apply -----------------------------------------------------

    def apply_replicated(self, seq: int, payload: bytes) -> bool:
        """Idempotent follower-side apply of one shipped op record.
        Returns False on an already-applied seq; raises ValueError on a
        gap (the tailer just re-fetches from ``next_seq`` — the registry
        WAL is never truncated, so the leader always still has it)."""
        with self._lock:
            nxt = self._wal.next_seq
            if seq < nxt:
                return False
            if seq > nxt:
                raise ValueError(
                    "registry replication gap: got seq %d, expected %d"
                    % (seq, nxt)
                )
            # lint: disable=GT002(registry WAL append ordering is this
            # lock's purpose; blocking_ok declared at construction)
            self._wal.append_at(seq, payload)
            self._apply_op(payload)
        return True

    # -- reads --------------------------------------------------------------

    @property
    def gen(self) -> int:
        with self._lock:
            return self._gen

    @property
    def next_seq(self) -> int:
        return self._wal.next_seq

    @property
    def wal(self) -> WriteAheadLog:
        return self._wal

    def get(self, sub_id: str):
        with self._lock:
            return self._subs.get(sub_id)

    def for_type(self, type_name: str) -> tuple:
        """Stable-ordered snapshot (registration order) — the matcher
        pairs layout row ids with this tuple, so order must be
        deterministic for a given generation."""
        with self._lock:
            return tuple(self._by_type.get(type_name, {}).values())

    def list(self, type_name: str | None = None) -> list:
        with self._lock:
            subs = self._subs.values()
            return [
                s.to_doc()
                for s in subs
                if type_name is None or s.type_name == type_name
            ]

    def count(self, type_name: str | None = None) -> int:
        with self._lock:
            if type_name is None:
                return len(self._subs)
            return len(self._by_type.get(type_name, ()))

    def stats(self) -> dict:
        with self._lock:
            by_type = {t: len(m) for t, m in self._by_type.items() if m}
            return {
                "subscriptions": len(self._subs),
                "by_type": by_type,
                "gen": self._gen,
                "wal": self._wal.stats(),
            }

    def close(self) -> None:
        self._wal.close()
