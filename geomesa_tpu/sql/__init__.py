"""Spatial SQL function library + SpatialFrame (the Spark integration
analog; ref: geomesa-spark geomesa-spark-sql -- SQLTypes,
GeometricConstructorFunctions, SpatialRelationFunctions, GeoMesaRelation
with spatial predicate pushdown [UNVERIFIED - empty reference mount]).

The reference registers ``st_*`` UDFs in Spark SQL and pushes spatial
predicates down into GeoMesa query planning. The TPU-native analog keeps
the same function names and semantics but vectorizes over columnar numpy
arrays directly (no JVM, no row UDF calls); SpatialFrame is the
DataFrame-shaped lazy view whose filters push down into the store's
planner (bbox/z3 pruning + fused device scan) instead of Spark relation
pushdown.
"""

from geomesa_tpu.sql.functions import (  # noqa: F401
    st_area,
    st_bufferPoint,
    st_centroid,
    st_contains,
    st_disjoint,
    st_distance,
    st_distanceSphere,
    st_dwithin,
    st_envelope,
    st_geomFromWKB,
    st_geomFromWKT,
    st_intersects,
    st_length,
    st_makeBBOX,
    st_numPoints,
    st_point,
    st_within,
    st_x,
    st_y,
)
from geomesa_tpu.sql.frame import SpatialFrame  # noqa: F401

__all__ = [
    "SpatialFrame",
    "st_point", "st_makeBBOX", "st_geomFromWKT", "st_geomFromWKB",
    "st_x", "st_y", "st_area", "st_length", "st_centroid", "st_envelope",
    "st_numPoints", "st_bufferPoint", "st_contains", "st_intersects",
    "st_within", "st_disjoint", "st_dwithin", "st_distance",
    "st_distanceSphere",
]
