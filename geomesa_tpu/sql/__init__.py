"""Spatial SQL function library + SpatialFrame (the Spark integration
analog; ref: geomesa-spark geomesa-spark-sql -- SQLTypes,
GeometricConstructorFunctions, GeometricAccessorFunctions,
GeometricOutputFunctions, GeometricProcessingFunctions,
SpatialRelationFunctions, GeoMesaRelation with spatial predicate pushdown,
and SpatialRDDProvider [UNVERIFIED - empty reference mount]).

The reference registers ``st_*`` UDFs in Spark SQL and pushes spatial
predicates down into GeoMesa query planning. The TPU-native analog keeps
the same function names and semantics but vectorizes over columnar numpy
arrays directly (no JVM, no row UDF calls); SpatialFrame is the
DataFrame-shaped lazy view whose filters push down into the store's
planner (bbox/z3 pruning + fused device scan) instead of Spark relation
pushdown, with ``partitions()``/``map_partitions()`` as the RDD analog
and ``spatial_join`` as the join pushdown.

Every ``st_*`` function is re-exported here and listed in ``FUNCTIONS``.
"""

from geomesa_tpu.sql.functions import FUNCTIONS  # noqa: F401
from geomesa_tpu.sql.functions import *  # noqa: F401,F403
from geomesa_tpu.sql.frame import SpatialFrame  # noqa: F401

__all__ = ["SpatialFrame", "FUNCTIONS", *sorted(FUNCTIONS)]
