"""SpatialFrame: the DataFrame-shaped lazy view over a store type (ref:
geomesa-spark GeoMesaRelation + SpatialFilterPushdown rule [UNVERIFIED -
empty reference mount]).

``frame.where("st_contains(...)  AND dtg > ...")`` composes ECQL filters
lazily; ``collect()`` pushes the whole conjunction into the store's query
planner (index choice, z-range prune, fused device scan) exactly like the
reference rebuilds GeoTools CQL from Spark SQL predicates. Post-relational
ops (select/limit/sort) ride the same Query so the planner applies them
server-side.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.query.plan import Query


@dataclass(frozen=True)
class SpatialFrame:
    store: object
    type_name: str
    _filter: ast.Filter = ast.Include
    _properties: "tuple[str, ...] | None" = None
    _limit: "int | None" = None
    _sort: "tuple[str, bool] | None" = None  # (attr, descending)
    _hints: dict = field(default_factory=dict)

    # -- composition -------------------------------------------------------

    def where(self, cql: "str | ast.Filter") -> "SpatialFrame":
        f = parse_ecql(cql) if isinstance(cql, str) else cql
        if self._filter is ast.Include:
            merged = f
        else:
            merged = ast.And((self._filter, f))
        return replace(self, _filter=merged)

    filter = where  # pyspark-style alias

    def select(self, *properties: str) -> "SpatialFrame":
        return replace(self, _properties=tuple(properties))

    def limit(self, n: int) -> "SpatialFrame":
        return replace(self, _limit=int(n))

    def sort(self, attr: str, descending: bool = False) -> "SpatialFrame":
        return replace(self, _sort=(attr, descending))

    orderBy = sort

    def with_auths(self, *auths: str) -> "SpatialFrame":
        h = dict(self._hints)
        h["auths"] = tuple(auths)
        return replace(self, _hints=h)

    # -- execution ---------------------------------------------------------

    def _query(self) -> Query:
        return Query(
            filter=self._filter,
            properties=list(self._properties) if self._properties else None,
            max_features=self._limit,
            sort_by=self._sort[0] if self._sort else None,
            sort_desc=self._sort[1] if self._sort else False,
            hints=dict(self._hints),
        )

    def collect(self):
        """Execute the pushed-down query -> FeatureBatch."""
        return self.store.query(self.type_name, self._query()).batch

    def count(self) -> int:
        return len(self.store.query(self.type_name, self._query()))

    def explain(self) -> str:
        return self.store.explain(self.type_name, self._query())

    def to_arrow(self):
        """Collect as a typed-vector pyarrow RecordBatch."""
        from geomesa_tpu.arrow_io import batch_to_arrow

        return batch_to_arrow(self.collect())

    def column(self, name: str) -> np.ndarray:
        return self.collect().column(name)

    def __len__(self) -> int:
        return self.count()
