"""SpatialFrame: the DataFrame-shaped lazy view over a store type (ref:
geomesa-spark GeoMesaRelation + SpatialFilterPushdown rule [UNVERIFIED -
empty reference mount]).

``frame.where("st_contains(...)  AND dtg > ...")`` composes ECQL filters
lazily; ``collect()`` pushes the whole conjunction into the store's query
planner (index choice, z-range prune, fused device scan) exactly like the
reference rebuilds GeoTools CQL from Spark SQL predicates. Post-relational
ops (select/limit/sort) ride the same Query so the planner applies them
server-side.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.query.plan import Query


@dataclass(frozen=True)
class SpatialFrame:
    store: object
    type_name: str
    _filter: ast.Filter = ast.Include
    _properties: "tuple[str, ...] | None" = None
    _limit: "int | None" = None
    _sort: "tuple[str, bool] | None" = None  # (attr, descending)
    _hints: dict = field(default_factory=dict)

    # -- composition -------------------------------------------------------

    def where(self, cql: "str | ast.Filter") -> "SpatialFrame":
        f = parse_ecql(cql) if isinstance(cql, str) else cql
        if self._filter is ast.Include:
            merged = f
        else:
            merged = ast.And((self._filter, f))
        return replace(self, _filter=merged)

    filter = where  # pyspark-style alias

    def select(self, *properties: str) -> "SpatialFrame":
        return replace(self, _properties=tuple(properties))

    def limit(self, n: int) -> "SpatialFrame":
        return replace(self, _limit=int(n))

    def sort(self, attr: str, descending: bool = False) -> "SpatialFrame":
        return replace(self, _sort=(attr, descending))

    orderBy = sort

    def with_auths(self, *auths: str) -> "SpatialFrame":
        h = dict(self._hints)
        h["auths"] = tuple(auths)
        return replace(self, _hints=h)

    # -- execution ---------------------------------------------------------

    def _query(self) -> Query:
        return Query(
            filter=self._filter,
            properties=list(self._properties) if self._properties else None,
            max_features=self._limit,
            sort_by=self._sort[0] if self._sort else None,
            sort_desc=self._sort[1] if self._sort else False,
            hints=dict(self._hints),
        )

    def collect(self):
        """Execute the pushed-down query -> FeatureBatch."""
        return self.store.query(self.type_name, self._query()).batch

    def count(self) -> int:
        return len(self.store.query(self.type_name, self._query()))

    def explain(self) -> str:
        return self.store.explain(self.type_name, self._query())

    def to_arrow(self):
        """Collect as a typed-vector pyarrow RecordBatch."""
        from geomesa_tpu.arrow_io import batch_to_arrow

        return batch_to_arrow(self.collect())

    def to_pandas(self):
        """Collect as a pandas DataFrame (fid index; geometries as
        objects, points as WKT like the reference's DataFrame view)."""
        import pandas as pd

        batch = self.collect()
        data = {}
        for name in batch.sft.attribute_names:
            c = batch.columns[name]
            desc = batch.sft.descriptor(name)
            if desc.is_point and c.dtype != object:
                from geomesa_tpu.geom import Point, to_wkt

                data[name] = [
                    to_wkt(Point(float(x), float(y))) for x, y in c
                ]
            elif desc.is_geometry:
                from geomesa_tpu.geom import to_wkt

                data[name] = [to_wkt(g) for g in c]
            elif desc.type_name == "Date":
                data[name] = np.array(c, dtype="datetime64[ms]")
            else:
                data[name] = c
        return pd.DataFrame(data, index=pd.Index(batch.fids, name="fid"))

    def column(self, name: str) -> np.ndarray:
        return self.collect().column(name)

    def __len__(self) -> int:
        return self.count()

    # -- partitioned execution (ref SpatialRDDProvider: 1 Spark partition
    # -- per range group; callers parallelize over the yielded batches) ----

    def partitions(self):
        """Yield per-storage-partition filtered FeatureBatches when the
        store supports partitioned scans, else one batch."""
        qp = getattr(self.store, "query_partitions", None)
        if qp is not None:
            yield from qp(self.type_name, self._query())
        else:
            b = self.collect()
            if len(b):
                yield b

    def map_partitions(self, fn, parallelism: "int | None" = None) -> list:
        """Apply ``fn`` to each partition batch on a thread pool (the
        executor-side compute analog; numpy releases the GIL enough for
        real overlap on IO-bound work)."""
        parts = list(self.partitions())
        if not parts:
            return []
        if parallelism is None or parallelism <= 1 or len(parts) == 1:
            return [fn(p) for p in parts]
        from concurrent.futures import ThreadPoolExecutor

        from geomesa_tpu.pyarrow_compat import preload_pyarrow

        preload_pyarrow()
        with ThreadPoolExecutor(max_workers=parallelism) as pool:
            return list(pool.map(fn, parts))

    # -- grouped aggregation ----------------------------------------------

    def value_counts(self, attr: str) -> dict:
        """Distinct values of ``attr`` -> feature count."""
        vals, counts = np.unique(self.column(attr), return_counts=True)
        return {v: int(c) for v, c in zip(vals.tolist(), counts.tolist())}

    def group_by(self, attr: str, agg_attr: str, agg: str = "count") -> dict:
        """Group rows by ``attr`` and aggregate ``agg_attr`` with one of
        count|sum|min|max|mean."""
        batch = self.collect()
        keys = batch.column(attr)
        vals = batch.column(agg_attr)
        fns = {
            "count": len,
            "sum": lambda v: float(np.sum(v)),
            "min": lambda v: float(np.min(v)),
            "max": lambda v: float(np.max(v)),
            "mean": lambda v: float(np.mean(v)),
        }
        if agg not in fns:
            raise ValueError(f"unknown aggregation {agg!r}")
        out: dict = {}
        for k in np.unique(keys).tolist():
            out[k] = fns[agg](vals[keys == k])
        return out

    # -- spatial join ------------------------------------------------------

    def spatial_join(
        self,
        other: "SpatialFrame",
        on: str = "intersects",
        distance: "float | None" = None,
        device_index=None,
    ):
        """Join this frame's features against ``other``'s on a spatial
        predicate (``intersects`` | ``contains`` | ``within`` |
        ``dwithin`` with ``distance``). Returns (left_batch, right_batch,
        pairs) where pairs is an (m, 2) index array into the two batches.

        Default path: the right side's collected envelope is pushed down
        into the left side's scan as a BBOX pre-filter (the reference's
        relation pushdown), then each right row's exact predicate runs
        vectorized over the left column — O(|R|) full-column passes.

        With a resident ``device_index`` over this frame's type, the
        coarse pass is instead a DEVICE join: every right row's padded
        envelope rides a runtime window array and candidate (row, window)
        pairs come back bit-packed (DeviceIndex.window_pairs_query, one
        dispatch per 64 right rows, 8B/row fetched), with this frame's
        filter fused on device; the exact predicate then refines each
        window's few candidates — O(candidates) instead of O(|R| x |L|).
        Falls back to the default path when the planes or the frame's
        filter are not device-resident. On the device path ``left`` is
        compacted to exactly the rows referenced by ``pairs`` (indices
        remapped accordingly); on the default path it is the
        bbox-pushed, filter-applied scan result, which may include rows
        no pair references. Address left rows through ``pairs`` for
        path-independent results.
        """
        from geomesa_tpu.sql import functions as F

        right = other.collect()
        geom_r = right.sft.geom_field
        rcol = right.columns[geom_r]
        preds = {
            "intersects": F.st_intersects,
            "contains": F.st_contains,
            "within": F.st_within,
        }
        if on == "dwithin" and distance is None:
            raise ValueError("dwithin join needs distance=")
        if on not in preds and on != "dwithin":
            raise ValueError(f"unknown join predicate {on!r}")

        if device_index is not None and len(right):
            got = self._device_join(
                device_index, right, rcol, on, distance, preds
            )
            if got is not None:
                return got

        # bbox pushdown from the right side's extent
        env = _extent(rcol)
        left_frame = self
        if env is not None:
            pad = distance or 0.0
            left_frame = self.where(
                ast.BBox(
                    _geom_field_of(self),
                    env[0] - pad,
                    env[1] - pad,
                    env[2] + pad,
                    env[3] + pad,
                )
            )
        left = left_frame.collect()
        lcol = left.columns[left.sft.geom_field]
        pairs = []
        for j in range(len(right)):
            g = _row_geom_of(rcol, j)
            if on == "dwithin":
                m = F.st_dwithin(lcol, g, distance)
            else:
                m = preds[on](lcol, g)
            for i in np.nonzero(np.asarray(m))[0]:
                pairs.append((int(i), j))
        return left, right, np.array(pairs, dtype=np.int64).reshape(-1, 2)

    def _device_join(self, di, right, rcol, on, distance, preds):
        """Device coarse pass + per-window exact refinement, or None when
        the resident planes / this frame's filter cannot serve it."""
        from geomesa_tpu.sql import functions as F

        pad = distance or 0.0
        envs = np.empty((len(right), 4), np.float64)
        for j in range(len(right)):
            e = _row_geom_of(rcol, j).envelope
            envs[j] = (e.xmin - pad, e.ymin - pad, e.xmax + pad, e.ymax + pad)
        base = self._filter if self._filter is not ast.Include else None
        got = di.window_pairs_query(envs, base=base)
        if got is None:
            return None
        rows, wins = got
        left = di._host_rows()
        lcol = left.columns[left.sft.geom_field]
        out_l: list = []
        out_r: list = []
        order = np.argsort(wins, kind="stable")
        rows, wins = rows[order], wins[order]
        starts = np.searchsorted(wins, np.arange(len(right)))
        ends = np.searchsorted(wins, np.arange(len(right)), side="right")
        for j in range(len(right)):
            cand = rows[starts[j] : ends[j]]
            if len(cand) == 0:
                continue
            g = _row_geom_of(rcol, j)
            sub = lcol[cand] if lcol.dtype == object else lcol[cand, :]
            if on == "dwithin":
                m = F.st_dwithin(sub, g, distance)
            else:
                m = preds[on](sub, g)
            hit = cand[np.nonzero(np.asarray(m))[0]]
            out_l.append(hit)
            out_r.append(np.full(len(hit), j, np.int64))
        pairs = (
            np.stack(
                [np.concatenate(out_l), np.concatenate(out_r)], axis=1
            )
            if out_l
            else np.empty((0, 2), np.int64)
        )
        # Compact the returned left batch to the rows the pairs actually
        # reference (remapping pair indices) so callers that consume
        # ``left`` directly never see the full resident mirror — the
        # default path's left is also a filtered subset, not all rows.
        if len(pairs):
            uniq, inv = np.unique(pairs[:, 0], return_inverse=True)
            left = left.take(uniq)
            pairs = np.stack([inv.astype(np.int64), pairs[:, 1]], axis=1)
        else:
            left = left.take(np.empty(0, np.int64))
        return left, right, pairs


def _geom_field_of(frame: SpatialFrame) -> str:
    return frame.store.get_schema(frame.type_name).geom_field


def _extent(col):
    if len(col) == 0:
        return None
    if col.dtype != object:
        return (
            float(col[:, 0].min()),
            float(col[:, 1].min()),
            float(col[:, 0].max()),
            float(col[:, 1].max()),
        )
    e = col[0].envelope
    for g in col[1:]:
        e = e.expand(g.envelope)
    return (e.xmin, e.ymin, e.xmax, e.ymax)


def _row_geom_of(col, i):
    from geomesa_tpu.sql.functions import _row_geom

    return _row_geom(col, i)
