"""SpatialFrame: the DataFrame-shaped lazy view over a store type (ref:
geomesa-spark GeoMesaRelation + SpatialFilterPushdown rule [UNVERIFIED -
empty reference mount]).

``frame.where("st_contains(...)  AND dtg > ...")`` composes ECQL filters
lazily; ``collect()`` pushes the whole conjunction into the store's query
planner (index choice, z-range prune, fused device scan) exactly like the
reference rebuilds GeoTools CQL from Spark SQL predicates. Post-relational
ops (select/limit/sort) ride the same Query so the planner applies them
server-side.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

import numpy as np

from geomesa_tpu.filter import ast
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.query.plan import Query


@dataclass(frozen=True)
class SpatialFrame:
    store: object
    type_name: str
    _filter: ast.Filter = ast.Include
    _properties: "tuple[str, ...] | None" = None
    _limit: "int | None" = None
    _sort: "tuple[str, bool] | None" = None  # (attr, descending)
    _hints: dict = field(default_factory=dict)

    # -- composition -------------------------------------------------------

    def where(self, cql: "str | ast.Filter") -> "SpatialFrame":
        f = parse_ecql(cql) if isinstance(cql, str) else cql
        if self._filter is ast.Include:
            merged = f
        else:
            merged = ast.And((self._filter, f))
        return replace(self, _filter=merged)

    filter = where  # pyspark-style alias

    def select(self, *properties: str) -> "SpatialFrame":
        return replace(self, _properties=tuple(properties))

    def limit(self, n: int) -> "SpatialFrame":
        return replace(self, _limit=int(n))

    def sort(self, attr: str, descending: bool = False) -> "SpatialFrame":
        return replace(self, _sort=(attr, descending))

    orderBy = sort

    def with_auths(self, *auths: str) -> "SpatialFrame":
        h = dict(self._hints)
        h["auths"] = tuple(auths)
        return replace(self, _hints=h)

    # -- execution ---------------------------------------------------------

    def _query(self) -> Query:
        return Query(
            filter=self._filter,
            properties=list(self._properties) if self._properties else None,
            max_features=self._limit,
            sort_by=self._sort[0] if self._sort else None,
            sort_desc=self._sort[1] if self._sort else False,
            hints=dict(self._hints),
        )

    def collect(self):
        """Execute the pushed-down query -> FeatureBatch."""
        return self.store.query(self.type_name, self._query()).batch

    def count(self) -> int:
        return len(self.store.query(self.type_name, self._query()))

    def explain(self) -> str:
        return self.store.explain(self.type_name, self._query())

    def to_arrow(self):
        """Collect as a typed-vector pyarrow RecordBatch."""
        from geomesa_tpu.arrow_io import batch_to_arrow

        return batch_to_arrow(self.collect())

    def to_pandas(self):
        """Collect as a pandas DataFrame (fid index; geometries as
        objects, points as WKT like the reference's DataFrame view)."""
        import pandas as pd

        batch = self.collect()
        data = {}
        for name in batch.sft.attribute_names:
            c = batch.columns[name]
            desc = batch.sft.descriptor(name)
            if desc.is_point and c.dtype != object:
                from geomesa_tpu.geom import Point, to_wkt

                data[name] = [
                    to_wkt(Point(float(x), float(y))) for x, y in c
                ]
            elif desc.is_geometry:
                from geomesa_tpu.geom import to_wkt

                data[name] = [to_wkt(g) for g in c]
            elif desc.type_name == "Date":
                data[name] = np.array(c, dtype="datetime64[ms]")
            else:
                data[name] = c
        return pd.DataFrame(data, index=pd.Index(batch.fids, name="fid"))

    def column(self, name: str) -> np.ndarray:
        return self.collect().column(name)

    def __len__(self) -> int:
        return self.count()

    # -- partitioned execution (ref SpatialRDDProvider: 1 Spark partition
    # -- per range group; callers parallelize over the yielded batches) ----

    def partitions(self):
        """Yield per-storage-partition filtered FeatureBatches when the
        store supports partitioned scans, else one batch."""
        qp = getattr(self.store, "query_partitions", None)
        if qp is not None:
            yield from qp(self.type_name, self._query())
        else:
            b = self.collect()
            if len(b):
                yield b

    def map_partitions(self, fn, parallelism: "int | None" = None) -> list:
        """Apply ``fn`` to each partition batch on a thread pool (the
        executor-side compute analog; numpy releases the GIL enough for
        real overlap on IO-bound work)."""
        parts = list(self.partitions())
        if not parts:
            return []
        if parallelism is None or parallelism <= 1 or len(parts) == 1:
            return [fn(p) for p in parts]
        from geomesa_tpu.pyarrow_compat import preload_pyarrow
        from geomesa_tpu.spawn import ContextPool

        preload_pyarrow()
        with ContextPool(parallelism, thread_name_prefix="sql-part") as pool:
            return list(pool.map(fn, parts))

    # -- grouped aggregation ----------------------------------------------

    def value_counts(self, attr: str) -> dict:
        """Distinct values of ``attr`` -> feature count."""
        vals, counts = np.unique(self.column(attr), return_counts=True)
        return {v: int(c) for v, c in zip(vals.tolist(), counts.tolist())}

    def group_by(self, attr: str, agg_attr: str, agg: str = "count") -> dict:
        """Group rows by ``attr`` and aggregate ``agg_attr`` with one of
        count|sum|min|max|mean."""
        batch = self.collect()
        keys = batch.column(attr)
        vals = batch.column(agg_attr)
        fns = {
            "count": len,
            "sum": lambda v: float(np.sum(v)),
            "min": lambda v: float(np.min(v)),
            "max": lambda v: float(np.max(v)),
            "mean": lambda v: float(np.mean(v)),
        }
        if agg not in fns:
            raise ValueError(f"unknown aggregation {agg!r}")
        out: dict = {}
        for k in np.unique(keys).tolist():
            out[k] = fns[agg](vals[keys == k])
        return out

    # -- spatial join ------------------------------------------------------

    def spatial_join(
        self,
        other: "SpatialFrame",
        on: str = "intersects",
        distance: "float | None" = None,
        device_index=None,
        sched=None,
        mesh=None,
    ):
        """Join this frame's features against ``other``'s on a spatial
        predicate (``intersects`` | ``contains`` | ``within`` |
        ``dwithin`` with ``distance``). Returns (left_batch, right_batch,
        pairs) where pairs is an (m, 2) index array into the two batches.

        Default path (also the parity ORACLE the engine is tested
        against): the right side's collected envelope is pushed down into
        the left side's scan as a BBOX pre-filter (the reference's
        relation pushdown), then each right row's candidates come from a
        sorted-coordinate interval prefilter and only they run the exact
        vectorized predicate — numpy end to end, no per-row interpreter
        work.

        With a resident ``device_index`` over this frame's type, the
        join routes through the JOIN ENGINE (geomesa_tpu/join): Z-range
        co-partitioned candidate planning with adaptive strategy
        selection (broadcast / grouped / zmerge, ``join.*`` conf keys),
        batched count->cap->compact refinement, this frame's filter and
        the index's visibility verdict applied as a row gate, and the
        exact predicate refining each window's few candidates —
        O(candidates) instead of O(|R| x |L|). A ``sched`` rides the
        refinement batches through the device query scheduler; a
        ``mesh`` runs them co-partitioned across its shards. On the
        engine path ``left`` is compacted to exactly the rows referenced
        by ``pairs`` (indices remapped accordingly); on the default path
        it is the bbox-pushed, filter-applied scan result, which may
        include rows no pair references. Address left rows through
        ``pairs`` for path-independent results.
        """
        from geomesa_tpu.sql import functions as F

        right = other.collect()
        geom_r = right.sft.geom_field
        rcol = right.columns[geom_r]
        preds = {
            "intersects": F.st_intersects,
            "contains": F.st_contains,
            "within": F.st_within,
        }
        if on == "dwithin" and distance is None:
            raise ValueError("dwithin join needs distance=")
        if on not in preds and on != "dwithin":
            raise ValueError(f"unknown join predicate {on!r}")

        if device_index is not None and len(right):
            got = self._engine_join(
                device_index, right, geom_r, rcol, on, distance, preds,
                sched, mesh,
            )
            if got is not None:
                return got

        # bbox pushdown from the right side's extent
        env = _extent(rcol)
        left_frame = self
        if env is not None:
            pad = distance or 0.0
            left_frame = self.where(
                ast.BBox(
                    _geom_field_of(self),
                    env[0] - pad,
                    env[1] - pad,
                    env[2] + pad,
                    env[3] + pad,
                )
            )
        left = left_frame.collect()
        lcol = left.columns[left.sft.geom_field]
        pairs = _reference_pairs(lcol, rcol, on, distance, preds)
        return left, right, pairs

    def _engine_join(self, di, right, geom_r, rcol, on, distance, preds,
                     sched, mesh=None):
        """Join-engine coarse pass (planned, co-partitioned, batched)
        + per-window exact refinement; None when the index cannot serve
        it (no geometry schema) — the caller falls back to the oracle
        path."""
        from geomesa_tpu.join import JoinEngine

        try:
            eng = JoinEngine(di, sched=sched, mesh=mesh)
            eng.prepare()
        except (ValueError, AttributeError):
            return None
        pad = distance or 0.0
        envs = right.bboxes(geom_r).astype(np.float64)
        if pad:
            envs = envs + np.array([-pad, -pad, pad, pad])
        base = self._filter if self._filter is not ast.Include else None
        gate = None
        if base is not None:
            # the frame filter (any shape — the mask path falls back to
            # host evaluation for non-device filters) plus validity and
            # the fail-closed visibility verdict, as one row gate
            from geomesa_tpu.join.engine import filter_gate

            gate = filter_gate(di, base)
        res = eng.join(envs, gate=gate)
        rows, wins = res.rows, res.wins
        left = di._host_rows()
        lcol = left.columns[left.sft.geom_field]
        rows, wins = _exact_residual(
            lcol, rcol, rows, wins, len(right), on, distance, preds
        )
        pairs = (
            np.stack([rows, wins], axis=1)
            if len(rows)
            else np.empty((0, 2), np.int64)
        )
        # Compact the returned left batch to the rows the pairs actually
        # reference (remapping pair indices) so callers that consume
        # ``left`` directly never see the full resident mirror — the
        # default path's left is also a filtered subset, not all rows.
        if len(pairs):
            uniq, inv = np.unique(pairs[:, 0], return_inverse=True)
            left = left.take(uniq)
            pairs = np.stack([inv.astype(np.int64), pairs[:, 1]], axis=1)
        else:
            left = left.take(np.empty(0, np.int64))
        return left, right, pairs


def _reference_pairs(lcol, rcol, on, distance, preds) -> np.ndarray:
    """The numpy host-reference join (the engine's parity oracle):
    per right row, a sorted-coordinate / envelope interval prefilter
    narrows the left side to candidates, then the SAME vectorized exact
    predicate the full-column scan would run decides — identical pairs
    (elementwise predicates), without the old O(n x m) interpreter-time
    pass over every row per window. Pairs sorted (right, left)."""
    from geomesa_tpu.sql import functions as F

    n, m = len(lcol), len(rcol)
    if n == 0 or m == 0:
        return np.empty((0, 2), np.int64)
    pad = distance or 0.0
    out_l: list = []
    out_r: list = []
    if lcol.dtype != object:
        # point left side: one stable argsort of x, then each window is
        # a searchsorted interval (a superset: the exact predicate
        # implies the point lies inside the padded envelope's x-range)
        xv = np.asarray(lcol[:, 0], np.float64)
        xo = np.argsort(xv, kind="stable")
        xs = xv[xo]
        for j in range(m):
            g = _row_geom_of(rcol, j)
            e = g.envelope
            lo = np.searchsorted(xs, e.xmin - pad, side="left")
            hi = np.searchsorted(xs, e.xmax + pad, side="right")
            if hi <= lo:
                continue
            cand = xo[lo:hi]
            sub = lcol[cand]
            if on == "dwithin":
                hit = F.st_dwithin(sub, g, distance)
            else:
                hit = preds[on](sub, g)
            ids = cand[np.asarray(hit)]
            if len(ids):
                out_l.append(np.sort(ids))
                out_r.append(np.full(len(ids), j, np.int64))
    else:
        # non-point left side: per-row envelopes once (O(n) total, not
        # O(n x m)), then each window prefilters by envelope overlap
        envs_l = np.empty((n, 4), np.float64)
        for i in range(n):
            e = lcol[i].envelope
            envs_l[i] = (e.xmin, e.ymin, e.xmax, e.ymax)
        for j in range(m):
            g = _row_geom_of(rcol, j)
            e = g.envelope
            cand = np.nonzero(
                (envs_l[:, 2] >= e.xmin - pad)
                & (envs_l[:, 0] <= e.xmax + pad)
                & (envs_l[:, 3] >= e.ymin - pad)
                & (envs_l[:, 1] <= e.ymax + pad)
            )[0]
            if not len(cand):
                continue
            sub = lcol[cand]
            if on == "dwithin":
                hit = F.st_dwithin(sub, g, distance)
            else:
                hit = preds[on](sub, g)
            ids = cand[np.asarray(hit)]  # cand ascending -> ids ascending
            if len(ids):
                out_l.append(ids)
                out_r.append(np.full(len(ids), j, np.int64))
    if not out_l:
        return np.empty((0, 2), np.int64)
    return np.stack(
        [
            np.concatenate(out_l).astype(np.int64),
            np.concatenate(out_r),
        ],
        axis=1,
    )


def _exact_residual(lcol, rcol, rows, wins, m, on, distance, preds):
    """Exact-predicate refinement of engine-emitted envelope pairs,
    grouped per window (pairs arrive window-sorted): the same vectorized
    predicate calls the reference path makes, over each window's few
    candidates instead of the whole column."""
    from geomesa_tpu.sql import functions as F

    if len(rows) == 0:
        return rows, wins
    starts = np.searchsorted(wins, np.arange(m))
    ends = np.searchsorted(wins, np.arange(m), side="right")
    keep = np.zeros(len(rows), bool)
    for j in range(m):
        s, e = starts[j], ends[j]
        if s == e:
            continue
        cand = rows[s:e]
        g = _row_geom_of(rcol, j)
        sub = lcol[cand] if lcol.dtype == object else lcol[cand, :]
        if on == "dwithin":
            hit = F.st_dwithin(sub, g, distance)
        else:
            hit = preds[on](sub, g)
        keep[s:e] = np.asarray(hit)
    return rows[keep], wins[keep]


def _geom_field_of(frame: SpatialFrame) -> str:
    return frame.store.get_schema(frame.type_name).geom_field


def _extent(col):
    if len(col) == 0:
        return None
    if col.dtype != object:
        return (
            float(col[:, 0].min()),
            float(col[:, 1].min()),
            float(col[:, 0].max()),
            float(col[:, 1].max()),
        )
    e = col[0].envelope
    for g in col[1:]:
        e = e.expand(g.envelope)
    return (e.xmin, e.ymin, e.xmax, e.ymax)


def _row_geom_of(col, i):
    from geomesa_tpu.sql.functions import _row_geom

    return _row_geom(col, i)
