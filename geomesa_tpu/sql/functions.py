"""Vectorized ``st_*`` spatial functions (ref: geomesa-spark-sql
GeometricConstructorFunctions / GeometricAccessorFunctions /
SpatialRelationFunctions / GeometricProcessingFunctions [UNVERIFIED -
empty reference mount]).

Conventions:
- A *point column* is an (n, 2) float64 array; a *geometry column* is an
  object array of geom.base Geometry; a scalar Geometry broadcasts.
- Relations return bool arrays (or bool for scalar/scalar).
- Names and argument order mirror the reference's Spark UDFs
  (``st_contains(a, b)`` = a contains b).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geom.base import (
    Envelope,
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geom.predicates import (
    geometry_crosses,
    geometry_intersects,
    geometry_overlaps,
    geometry_relate,
    geometry_relate_matches,
    geometry_touches,
    geometry_within,
    points_in_polygon,
)

EARTH_RADIUS_M = 6_371_008.8


# -- constructors ------------------------------------------------------------


def st_point(x, y):
    """(x, y) columns -> point column; scalars -> Point."""
    if np.isscalar(x) and np.isscalar(y):
        return Point(float(x), float(y))
    return np.stack(
        [np.asarray(x, np.float64), np.asarray(y, np.float64)], axis=1
    )


def st_makeBBOX(xmin: float, ymin: float, xmax: float, ymax: float) -> Polygon:
    return Polygon(
        np.array(
            [
                (xmin, ymin),
                (xmax, ymin),
                (xmax, ymax),
                (xmin, ymax),
                (xmin, ymin),
            ],
            dtype=np.float64,
        )
    )


def st_geomFromWKT(wkt):
    from geomesa_tpu.geom.wkt import parse_wkt

    if isinstance(wkt, str):
        return parse_wkt(wkt)
    return np.array([parse_wkt(w) for w in wkt], dtype=object)


def st_geomFromWKB(wkb):
    from geomesa_tpu.geom.wkb import from_wkb

    if isinstance(wkb, (bytes, bytearray)):
        return from_wkb(bytes(wkb))
    return np.array([from_wkb(bytes(w)) for w in wkb], dtype=object)


# -- accessors ---------------------------------------------------------------


def _is_point_col(col) -> bool:
    return (
        isinstance(col, np.ndarray) and col.dtype != object and col.ndim == 2
    )


def st_x(geom):
    if isinstance(geom, Point):
        return geom.x
    if _is_point_col(geom):
        return np.ascontiguousarray(geom[:, 0])
    return np.array(
        [g.x if isinstance(g, Point) else np.nan for g in geom]
    )


def st_y(geom):
    if isinstance(geom, Point):
        return geom.y
    if _is_point_col(geom):
        return np.ascontiguousarray(geom[:, 1])
    return np.array(
        [g.y if isinstance(g, Point) else np.nan for g in geom]
    )


def st_envelope(geom):
    """Envelope (or array of Envelope) of geometries."""
    if isinstance(geom, Geometry):
        return geom.envelope
    if _is_point_col(geom):
        return np.array(
            [Envelope(x, y, x, y) for x, y in geom], dtype=object
        )
    return np.array([g.envelope for g in geom], dtype=object)


def _ring_area(r: np.ndarray) -> float:
    x, y = r[:, 0], r[:, 1]
    return 0.5 * float(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1]))


def _geom_area(g) -> float:
    if isinstance(g, Polygon):
        shell = abs(_ring_area(g.shell))
        return shell - sum(abs(_ring_area(h)) for h in g.holes)
    if isinstance(g, MultiPolygon):
        return sum(_geom_area(p) for p in g.polygons)
    return 0.0


def st_area(geom):
    if isinstance(geom, Geometry):
        return _geom_area(geom)
    if _is_point_col(geom):
        return np.zeros(len(geom))
    return np.array([_geom_area(g) for g in geom])


def _geom_length(g) -> float:
    if isinstance(g, LineString):
        d = np.diff(g.coords, axis=0)
        return float(np.hypot(d[:, 0], d[:, 1]).sum())
    if isinstance(g, MultiLineString):
        return sum(_geom_length(l) for l in g.lines)
    if isinstance(g, Polygon):
        return sum(
            float(np.hypot(*np.diff(r, axis=0).T).sum()) for r in g.rings()
        )
    if isinstance(g, MultiPolygon):
        return sum(_geom_length(p) for p in g.polygons)
    return 0.0


def st_length(geom):
    if isinstance(geom, Geometry):
        return _geom_length(geom)
    if _is_point_col(geom):
        return np.zeros(len(geom))
    return np.array([_geom_length(g) for g in geom])


def _geom_centroid(g) -> Point:
    if isinstance(g, Point):
        return g
    vs = _all_vertices(g)
    return Point(float(vs[:, 0].mean()), float(vs[:, 1].mean()))


def _all_vertices(g) -> np.ndarray:
    if isinstance(g, Point):
        return np.array([[g.x, g.y]])
    if isinstance(g, LineString):
        return g.coords
    if isinstance(g, Polygon):
        return g.shell[:-1]
    if isinstance(g, MultiPoint):
        return np.array([[p.x, p.y] for p in g.points])
    if isinstance(g, MultiLineString):
        return np.concatenate([l.coords for l in g.lines])
    if isinstance(g, MultiPolygon):
        return np.concatenate([p.shell[:-1] for p in g.polygons])
    raise TypeError(type(g))


def st_centroid(geom):
    if isinstance(geom, Geometry):
        return _geom_centroid(geom)
    if _is_point_col(geom):
        return geom.copy()
    return np.array([_geom_centroid(g) for g in geom], dtype=object)


def st_numPoints(geom):
    def n(g):
        return len(_all_vertices(g)) if not isinstance(g, Point) else 1

    if isinstance(geom, Geometry):
        return n(geom)
    if _is_point_col(geom):
        return np.ones(len(geom), dtype=np.int64)
    return np.array([n(g) for g in geom], dtype=np.int64)


def st_bufferPoint(geom, distance_m: float, segments: int = 32):
    """Geodesic-ish circular buffer around point(s) in meters (ref
    st_bufferPoint: degrees-from-meters at the point's latitude)."""

    def circle(x, y):
        dlat = np.degrees(distance_m / EARTH_RADIUS_M)
        dlon = dlat / max(np.cos(np.radians(y)), 1e-9)
        t = np.linspace(0.0, 2 * np.pi, segments + 1)
        ring = np.stack(
            [x + dlon * np.cos(t), y + dlat * np.sin(t)], axis=1
        )
        ring[-1] = ring[0]
        return Polygon(ring)

    if isinstance(geom, Point):
        return circle(geom.x, geom.y)
    if _is_point_col(geom):
        return np.array([circle(x, y) for x, y in geom], dtype=object)
    return np.array(
        [circle(g.x, g.y) for g in geom], dtype=object
    )


# -- relations ---------------------------------------------------------------


def _as_geom_scalar(g):
    return g if isinstance(g, Geometry) else None


def _pairwise(a, b, fn, point_fast=None):
    """Broadcast a relation over (column, scalar), (scalar, column),
    (column, column) or (scalar, scalar) inputs."""
    a_scalar = isinstance(a, Geometry)
    b_scalar = isinstance(b, Geometry)
    if a_scalar and b_scalar:
        return fn(a, b)
    if _is_point_col(a) and b_scalar and point_fast is not None:
        return point_fast(a, b, False)
    if a_scalar and _is_point_col(b) and point_fast is not None:
        return point_fast(b, a, True)
    av = a if not a_scalar else None
    bv = b if not b_scalar else None
    n = len(av) if av is not None else len(bv)
    out = np.empty(n, dtype=bool)
    for i in range(n):
        ga = a if a_scalar else _row_geom(a, i)
        gb = b if b_scalar else _row_geom(b, i)
        out[i] = fn(ga, gb)
    return out


def _row_geom(col, i):
    if _is_point_col(col):
        return Point(float(col[i, 0]), float(col[i, 1]))
    return col[i]


def _points_vs_geom_intersects(pts: np.ndarray, g: Geometry, flipped: bool):
    # symmetric relation: ignore flipped
    if isinstance(g, (Polygon, MultiPolygon)):
        x, y = pts[:, 0], pts[:, 1]
        if isinstance(g, Polygon):
            return points_in_polygon(x, y, g.rings())
        m = np.zeros(len(pts), dtype=bool)
        for p in g.polygons:
            m |= points_in_polygon(x, y, p.rings())
        return m
    out = np.empty(len(pts), dtype=bool)
    for i in range(len(pts)):
        out[i] = geometry_intersects(
            Point(float(pts[i, 0]), float(pts[i, 1])), g
        )
    return out


def st_intersects(a, b):
    return _pairwise(
        a, b, geometry_intersects, point_fast=_points_vs_geom_intersects
    )


def st_disjoint(a, b):
    r = st_intersects(a, b)
    return ~r if isinstance(r, np.ndarray) else not r


def st_contains(a, b):
    """a contains b (b within a)."""

    def fn(ga, gb):
        return geometry_within(gb, ga)

    def pf(pts, g, flipped):
        if flipped:
            # pts contains g: a point only contains an equal point
            if isinstance(g, Point):
                return (pts[:, 0] == g.x) & (pts[:, 1] == g.y)
            return np.zeros(len(pts), dtype=bool)
        return _points_vs_geom_intersects(pts, g, False) if isinstance(
            g, (Polygon, MultiPolygon)
        ) else np.array(
            [fn(_row_geom(pts, i), g) for i in range(len(pts))]
        )

    # st_contains(scalar_geom, point_col): the common pushdown shape
    if isinstance(a, Geometry) and not isinstance(b, Geometry):
        if _is_point_col(b):
            return pf(b, a, False)
        return np.array([fn(a, gb) for gb in b], dtype=bool)
    if isinstance(b, Geometry) and not isinstance(a, Geometry):
        if _is_point_col(a):
            return pf(a, b, True)
        return np.array([fn(ga, b) for ga in a], dtype=bool)
    return _pairwise(a, b, fn)


def st_within(a, b):
    """a within b."""
    return st_contains(b, a)


def st_crosses(a, b):
    """OGC crosses (ref SpatialRelationFunctions.ST_Crosses [UNVERIFIED -
    empty reference mount]): interiors meet in a lower dimension and each
    geometry extends outside the other."""
    return _pairwise(a, b, geometry_crosses)


def st_touches(a, b):
    """OGC touches: geometries meet only at their boundaries."""
    return _pairwise(a, b, geometry_touches)


def st_overlaps(a, b):
    """OGC overlaps: same dimension, interiors partially shared, neither
    covers the other."""
    return _pairwise(a, b, geometry_overlaps)


def st_relate(a, b):
    """DE-9IM-lite matrix string per pair ('T'/'F' cells; dimension digits
    are not computed -- see geom.predicates.relate_matches)."""
    if isinstance(a, Geometry) and isinstance(b, Geometry):
        return geometry_relate(a, b)
    av = a if not isinstance(a, Geometry) else None
    bv = b if not isinstance(b, Geometry) else None
    n = len(av) if av is not None else len(bv)
    out = np.empty(n, dtype=object)
    for i in range(n):
        ga = a if av is None else _row_geom(a, i)
        gb = b if bv is None else _row_geom(b, i)
        out[i] = geometry_relate(ga, gb)
    return out


def st_relateBool(a, b, pattern: str):
    """DE-9IM-lite pattern match (ref ST_RelateBool)."""

    def fn(ga, gb):
        return geometry_relate_matches(ga, gb, pattern)

    return _pairwise(a, b, fn)


def _segments_of(g) -> np.ndarray:
    """(m, 4) [x0 y0 x1 y1] edge list (rings include holes, via the shared
    predicates helper); point-like geometries yield zero-length segments so
    one distance formula covers every pair."""
    from geomesa_tpu.geom.predicates import _segments_of as _geom_segments

    segs = _geom_segments(g)
    if segs is not None:
        return segs
    va = _all_vertices(g)
    return np.concatenate([va, va], axis=1)


def pt_seg_project(pts: np.ndarray, segs: np.ndarray):
    """Clamped projection of each point onto each segment. ``pts`` is
    (n, 2), ``segs`` is (m, 4) as [x0, y0, x1, y1]. Returns ``(t, dist2)``
    with shape (n, m): the clamped parameter along each segment and the
    squared point-to-segment distance."""
    p = pts[:, None, :]
    a = segs[None, :, 0:2]
    d = segs[None, :, 2:4] - a
    len2 = (d**2).sum(-1)
    t = ((p - a) * d).sum(-1) / np.where(len2 == 0, 1.0, len2)
    t = np.clip(np.where(len2 == 0, 0.0, t), 0.0, 1.0)
    near = a + t[..., None] * d
    return t, ((p - near) ** 2).sum(-1)


def _pt_seg_dist(pts: np.ndarray, segs: np.ndarray) -> float:
    """min over all (point, segment) pairs of the exact point-to-segment
    distance (clamped projection)."""
    _, dist2 = pt_seg_project(pts, segs)
    return float(np.sqrt(dist2.min()))


def st_distance(a, b):
    """Exact planar distance: 0 when intersecting, else the minimum
    point-to-segment distance both ways (exact for non-crossing
    geometries, since any crossing pair would have intersected)."""

    def fn(ga, gb):
        if isinstance(ga, Point) and isinstance(gb, Point):
            return float(np.hypot(ga.x - gb.x, ga.y - gb.y))
        if geometry_intersects(ga, gb):
            return 0.0
        # point sets come from the segment endpoints so hole-ring vertices
        # participate (shells alone would overestimate near holes)
        sa, sb = _segments_of(ga), _segments_of(gb)
        pa = np.concatenate([sa[:, 0:2], sa[:, 2:4]], axis=0)
        pb = np.concatenate([sb[:, 0:2], sb[:, 2:4]], axis=0)
        return min(_pt_seg_dist(pa, sb), _pt_seg_dist(pb, sa))

    if isinstance(a, Geometry) and isinstance(b, Geometry):
        return fn(a, b)
    if _is_point_col(a) and isinstance(b, Point):
        return np.hypot(a[:, 0] - b.x, a[:, 1] - b.y)
    if _is_point_col(b) and isinstance(a, Point):
        return np.hypot(b[:, 0] - a.x, b[:, 1] - a.y)
    if _is_point_col(a) and _is_point_col(b):
        return np.hypot(a[:, 0] - b[:, 0], a[:, 1] - b[:, 1])
    n = len(a) if not isinstance(a, Geometry) else len(b)
    return np.array(
        [
            fn(
                a if isinstance(a, Geometry) else _row_geom(a, i),
                b if isinstance(b, Geometry) else _row_geom(b, i),
            )
            for i in range(n)
        ]
    )


def st_dwithin(a, b, distance: float):
    d = st_distance(a, b)
    return d <= distance


def st_distanceSphere(a, b):
    """Haversine great-circle distance in meters between points/point
    columns (ref st_distanceSpheroid's spherical sibling)."""

    def coords(v):
        if isinstance(v, Point):
            return np.array([v.x]), np.array([v.y])
        if _is_point_col(v):
            return v[:, 0], v[:, 1]
        return (
            np.array([g.x for g in v]),
            np.array([g.y for g in v]),
        )

    ax, ay = coords(a)
    bx, by = coords(b)
    lat1, lat2 = np.radians(ay), np.radians(by)
    dlat = lat2 - lat1
    dlon = np.radians(bx - ax)
    h = (
        np.sin(dlat / 2) ** 2
        + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    )
    d = 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(h, 0, 1)))
    if isinstance(a, Point) and isinstance(b, Point):
        return float(d[0])
    return d


# -- scalar-mapping helper ---------------------------------------------------


def _map_geoms(geom, fn):
    """Apply a Geometry -> value function over a scalar or column input."""
    if isinstance(geom, Geometry):
        return fn(geom)
    if _is_point_col(geom):
        return np.array(
            [fn(Point(float(x), float(y))) for x, y in geom], dtype=object
        )
    return np.array([fn(g) for g in geom], dtype=object)


# -- typed constructors (ref GeometricConstructorFunctions) ------------------


def st_makeLine(points) -> LineString:
    """Points (Point list or (n, 2) array) -> LineString."""
    if isinstance(points, np.ndarray):
        return LineString(points)
    return LineString(
        np.array([[p.x, p.y] for p in points], dtype=np.float64)
    )


def st_makePolygon(line) -> Polygon:
    """Closed LineString (or coords) -> Polygon shell."""
    coords = line.coords if isinstance(line, LineString) else np.asarray(line)
    if not np.array_equal(coords[0], coords[-1]):
        coords = np.concatenate([coords, coords[:1]], axis=0)
    return Polygon(coords)


st_makeBox2D = st_makeBBOX  # ref alias (two corner points in the reference)


def _typed_from_text(wkt, cls, name):
    g = st_geomFromWKT(wkt)
    if isinstance(g, np.ndarray):
        if any(not isinstance(v, cls) for v in g):
            raise ValueError(f"{name} got non-{cls.__name__} WKT")
        return g
    if not isinstance(g, cls):
        raise ValueError(f"{name} got {type(g).__name__}, not {cls.__name__}")
    return g


def st_pointFromText(wkt):
    return _typed_from_text(wkt, Point, "st_pointFromText")


def st_lineFromText(wkt):
    return _typed_from_text(wkt, LineString, "st_lineFromText")


def st_polygonFromText(wkt):
    return _typed_from_text(wkt, Polygon, "st_polygonFromText")


def st_mPointFromText(wkt):
    return _typed_from_text(wkt, MultiPoint, "st_mPointFromText")


def st_mLineFromText(wkt):
    return _typed_from_text(wkt, MultiLineString, "st_mLineFromText")


def st_mPolyFromText(wkt):
    return _typed_from_text(wkt, MultiPolygon, "st_mPolyFromText")


def st_geomFromGeoJSON(doc):
    from geomesa_tpu.geom.geojson import from_geojson

    if isinstance(doc, (dict, str, bytes)):
        return from_geojson(doc)
    return np.array([from_geojson(d) for d in doc], dtype=object)


def st_geomFromGeoHash(gh, precision: "int | None" = None):
    """GeoHash string -> its cell Polygon."""
    from geomesa_tpu.geom import geohash

    def one(h):
        # precision counts geohash characters, same unit as st_geoHash
        (xmin, xmax), (ymin, ymax) = geohash.decode_bbox(
            h if precision is None else h[:precision]
        )
        return st_makeBBOX(xmin, ymin, xmax, ymax)

    if isinstance(gh, str):
        return one(gh)
    return np.array([one(h) for h in gh], dtype=object)


st_box2DFromGeoHash = st_geomFromGeoHash  # ref alias


def st_pointFromGeoHash(gh, precision: "int | None" = None):
    """GeoHash string -> cell-center Point."""
    from geomesa_tpu.geom import geohash

    def one(h):
        lon, lat = geohash.decode(h)
        return Point(lon, lat)

    if isinstance(gh, str):
        return one(gh)
    return np.array([one(h) for h in gh], dtype=object)


def st_castToPoint(geom):
    return _cast(geom, Point)


def st_castToLineString(geom):
    return _cast(geom, LineString)


def st_castToPolygon(geom):
    return _cast(geom, Polygon)


def _cast(geom, cls):
    def one(g):
        if not isinstance(g, cls):
            raise ValueError(f"cannot cast {type(g).__name__} to {cls.__name__}")
        return g

    if isinstance(geom, Geometry):
        return one(geom)
    return _map_geoms(geom, one)


# -- accessors (ref GeometricAccessorFunctions) ------------------------------


def st_geometryType(geom):
    return _scalar_or_col(geom, lambda g: type(g).__name__)


def _scalar_or_col(geom, fn):
    if isinstance(geom, Geometry):
        return fn(geom)
    return _map_geoms(geom, fn)


def st_isEmpty(geom):
    def one(g):
        if isinstance(g, Point):
            return bool(np.isnan(g.x))
        if isinstance(g, LineString):
            return len(g.coords) == 0
        if isinstance(g, Polygon):
            return len(g.shell) == 0
        if isinstance(g, MultiPoint):
            return len(g.points) == 0
        if isinstance(g, MultiLineString):
            return len(g.lines) == 0
        if isinstance(g, MultiPolygon):
            return len(g.polygons) == 0
        return False

    return _scalar_or_col(geom, one)


def st_isCollection(geom):
    return _scalar_or_col(
        geom,
        lambda g: isinstance(g, (MultiPoint, MultiLineString, MultiPolygon)),
    )


def st_isClosed(geom):
    """Lines: first == last coordinate (points/polygons are closed)."""

    def one(g):
        if isinstance(g, LineString):
            return bool(np.array_equal(g.coords[0], g.coords[-1]))
        if isinstance(g, MultiLineString):
            return all(
                np.array_equal(l.coords[0], l.coords[-1]) for l in g.lines
            )
        return True

    return _scalar_or_col(geom, one)


def st_isRing(geom):
    def one(g):
        return isinstance(g, LineString) and bool(
            np.array_equal(g.coords[0], g.coords[-1])
        )

    return _scalar_or_col(geom, one)


def st_dimension(geom):
    def one(g):
        if isinstance(g, (Point, MultiPoint)):
            return 0
        if isinstance(g, (LineString, MultiLineString)):
            return 1
        return 2

    return _scalar_or_col(geom, one)


def st_coordDim(geom):
    return _scalar_or_col(geom, lambda g: 2)  # xy-only geometry model


def st_numGeometries(geom):
    def one(g):
        if isinstance(g, MultiPoint):
            return len(g.points)
        if isinstance(g, MultiLineString):
            return len(g.lines)
        if isinstance(g, MultiPolygon):
            return len(g.polygons)
        return 1

    return _scalar_or_col(geom, one)


def st_geometryN(geom, n: int):
    """1-based part accessor (ref/JTS convention)."""

    def one(g):
        if isinstance(g, MultiPoint):
            return g.points[n - 1]
        if isinstance(g, MultiLineString):
            return g.lines[n - 1]
        if isinstance(g, MultiPolygon):
            return g.polygons[n - 1]
        if n != 1:
            raise IndexError(f"geometry has 1 part, asked for {n}")
        return g

    return _scalar_or_col(geom, one)


def st_exteriorRing(geom):
    def one(g):
        if isinstance(g, Polygon):
            return LineString(g.shell)
        raise ValueError("st_exteriorRing needs a Polygon")

    return _scalar_or_col(geom, one)


def st_interiorRingN(geom, n: int):
    def one(g):
        if isinstance(g, Polygon):
            return LineString(g.holes[n - 1])
        raise ValueError("st_interiorRingN needs a Polygon")

    return _scalar_or_col(geom, one)


def st_pointN(geom, n: int):
    """1-based vertex accessor on lines (negative counts from the end)."""

    def one(g):
        if not isinstance(g, LineString):
            raise ValueError("st_pointN needs a LineString")
        c = g.coords[n - 1 if n > 0 else n]
        return Point(float(c[0]), float(c[1]))

    return _scalar_or_col(geom, one)


def st_startPoint(geom):
    return st_pointN(geom, 1)


def st_endPoint(geom):
    return st_pointN(geom, -1)


# -- outputs (ref SpatialEncoders / output functions) ------------------------


def st_asText(geom):
    from geomesa_tpu.geom.wkt import to_wkt

    return _scalar_or_col(geom, to_wkt)


st_asWKT = st_asText


def st_asBinary(geom):
    from geomesa_tpu.geom.wkb import to_wkb

    return _scalar_or_col(geom, to_wkb)


st_asWKB = st_asBinary


def st_asTWKB(geom, precision: int = 7):
    from geomesa_tpu.geom.wkb import to_twkb

    return _scalar_or_col(geom, lambda g: to_twkb(g, precision))


def st_asGeoJSON(geom):
    import json

    from geomesa_tpu.geom.geojson import to_geojson

    return _scalar_or_col(geom, lambda g: json.dumps(to_geojson(g)))


def st_geoHash(geom, precision: int = 9):
    """Point (or point column) -> GeoHash string(s)."""
    from geomesa_tpu.geom import geohash

    if isinstance(geom, Point):
        return geohash.encode(geom.x, geom.y, precision)
    if _is_point_col(geom):
        return np.array(
            [geohash.encode(x, y, precision) for x, y in geom], dtype=object
        )

    def one(g):
        if not isinstance(g, Point):
            raise ValueError(
                f"st_geoHash needs Point geometries, got {type(g).__name__}"
            )
        return geohash.encode(g.x, g.y, precision)

    return _map_geoms(geom, one)


# -- processing (ref GeometricProcessingFunctions) ---------------------------


def _map_coords(g, fn):
    """Rebuild a geometry with transformed (n, 2) coordinate arrays."""
    if isinstance(g, Point):
        c = fn(np.array([[g.x, g.y]]))
        return Point(float(c[0, 0]), float(c[0, 1]))
    if isinstance(g, LineString):
        return LineString(fn(g.coords))
    if isinstance(g, Polygon):
        return Polygon(fn(g.shell), tuple(fn(h) for h in g.holes))
    if isinstance(g, MultiPoint):
        return MultiPoint(tuple(_map_coords(p, fn) for p in g.points))
    if isinstance(g, MultiLineString):
        return MultiLineString(tuple(_map_coords(l, fn) for l in g.lines))
    if isinstance(g, MultiPolygon):
        return MultiPolygon(tuple(_map_coords(p, fn) for p in g.polygons))
    raise ValueError(f"cannot transform {type(g).__name__}")


def st_translate(geom, dx: float, dy: float):
    def one(g):
        return _map_coords(g, lambda c: c + np.array([dx, dy]))

    return _scalar_or_col(geom, one)


def st_convexHull(geom):
    """Monotone-chain convex hull of all vertices."""

    def one(g):
        pts = np.unique(_all_vertices(g), axis=0)
        if len(pts) == 1:
            return Point(float(pts[0, 0]), float(pts[0, 1]))
        if len(pts) == 2:
            return LineString(pts)
        pts = pts[np.lexsort((pts[:, 1], pts[:, 0]))]

        def half(points):
            out = []
            for p in points:
                while len(out) >= 2:
                    u = out[-1] - out[-2]
                    v = p - out[-2]
                    if u[0] * v[1] - u[1] * v[0] <= 0:  # 2d cross product
                        out.pop()
                    else:
                        break
                out.append(p)
            return out

        lower = half(pts)
        upper = half(pts[::-1])
        hull = np.array(lower[:-1] + upper[:-1])
        if len(hull) < 3:
            return LineString(np.array([pts[0], pts[-1]]))
        return Polygon(np.concatenate([hull, hull[:1]], axis=0))

    return _scalar_or_col(geom, one)


def st_closestPoint(a, b):
    """Point on geometry ``a`` closest to point ``b``."""

    def one(ga, gb):
        if not isinstance(gb, Point):
            raise ValueError("st_closestPoint expects a Point second arg")
        if isinstance(ga, Point):
            return ga
        segs = _segments_of(ga)
        pt = np.array([[gb.x, gb.y]])
        t, dist2 = pt_seg_project(pt, segs)
        j = int(dist2[0].argmin())
        sa = segs[j, 0:2]
        sd = segs[j, 2:4] - sa
        c = sa + t[0, j] * sd
        return Point(float(c[0]), float(c[1]))

    if isinstance(a, Geometry) and isinstance(b, Point):
        return one(a, b)
    return _map_geoms(a, lambda g: one(g, b))


def st_lengthSphere(geom):
    """LineString length in meters over the sphere (haversine per segment)."""

    def one(g):
        segs = _segments_of(g)
        if len(segs) == 0:
            return 0.0
        lon1, lat1, lon2, lat2 = (
            np.radians(segs[:, 0]),
            np.radians(segs[:, 1]),
            np.radians(segs[:, 2]),
            np.radians(segs[:, 3]),
        )
        h = (
            np.sin((lat2 - lat1) / 2) ** 2
            + np.cos(lat1) * np.cos(lat2) * np.sin((lon2 - lon1) / 2) ** 2
        )
        return float(
            (2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(h, 0, 1)))).sum()
        )

    return _scalar_or_col(geom, one)


def st_antimeridianSafeGeom(geom):
    """Split geometries that extend past lon +/-180 into an in-range
    MultiPolygon/MultiLineString (ref st_antimeridianSafeGeom; the
    reference's buffer ops can produce lon > 180 which must be wrapped
    before indexing)."""

    def clip_ring(coords, boundary, keep_right):
        # Sutherland-Hodgman against the half-plane x <= boundary
        # (keep_right False) or x >= boundary (True)
        out = []
        n = len(coords)
        for i in range(n):
            cur, nxt = coords[i], coords[(i + 1) % n]
            cin = cur[0] >= boundary if keep_right else cur[0] <= boundary
            nin = nxt[0] >= boundary if keep_right else nxt[0] <= boundary
            if cin:
                out.append(cur)
            if cin != nin:
                tpar = (boundary - cur[0]) / (nxt[0] - cur[0])
                out.append(
                    np.array([boundary, cur[1] + tpar * (nxt[1] - cur[1])])
                )
        return np.array(out) if len(out) >= 3 else None

    def one(g):
        e = g.envelope
        if e.xmax <= 180.0 and e.xmin >= -180.0:
            return g
        if isinstance(g, Point):
            x = ((g.x + 180.0) % 360.0) - 180.0
            return Point(x, g.y)
        if isinstance(g, Polygon):
            if e.xmax > 180.0:  # spills east: split at +180
                boundary, kept_right, shift = 180.0, False, -360.0
            else:  # spills west: split at -180
                boundary, kept_right, shift = -180.0, True, 360.0

            def side(ring_, right):
                return clip_ring(ring_, boundary, keep_right=right)

            def close(r):
                return np.concatenate([r, r[:1]], axis=0)

            parts = []
            for right, dx in ((kept_right, 0.0), (not kept_right, shift)):
                shell = side(g.shell[:-1], right)
                if shell is None:
                    continue
                holes = []
                for h in g.holes:
                    hc = side(h[:-1], right)
                    if hc is not None:
                        holes.append(close(hc + np.array([dx, 0.0])))
                parts.append(
                    Polygon(close(shell + np.array([dx, 0.0])), tuple(holes))
                )
            if not parts:
                return g
            return parts[0] if len(parts) == 1 else MultiPolygon(tuple(parts))
        if isinstance(g, MultiPolygon):
            parts = []
            for p in g.polygons:
                r = one(p)
                parts.extend(
                    r.polygons if isinstance(r, MultiPolygon) else [r]
                )
            return MultiPolygon(tuple(parts))
        return g  # lines/others: left untouched

    return _scalar_or_col(geom, one)


st_idlSafeGeom = st_antimeridianSafeGeom  # ref alias


def st_equals(a, b):
    def fn(ga, gb):
        if type(ga) is not type(gb):
            return False
        if isinstance(ga, Point):
            return ga.x == gb.x and ga.y == gb.y
        va, vb = _all_vertices(ga), _all_vertices(gb)
        return va.shape == vb.shape and bool(np.allclose(va, vb))

    def point_fast(pts, g, flipped):
        if not isinstance(g, Point):
            return np.zeros(len(pts), dtype=bool)
        return (pts[:, 0] == g.x) & (pts[:, 1] == g.y)

    return _pairwise(a, b, fn, point_fast)


def st_covers(a, b):
    """a covers b (boundary-inclusive contains; approximated by contains
    with boundary tolerance on our grid model)."""
    return st_contains(a, b)


# -- constructor/cast aliases (ref naming variants) --------------------------

st_makePoint = st_point  # ref alias (jts constructor name)
st_geomFromText = st_geomFromWKT  # ref alias
st_geometryFromText = st_geomFromWKT  # ref alias


def st_makePointM(x, y, m):
    """(x, y, m) -> point; the measure coordinate is DROPPED (this
    framework's geometry model is 2-D — the reference's M rides JTS
    coordinates but no indexed operation reads it)."""
    return st_point(x, y)


def st_pointFromWKB(wkb):
    """WKB -> Point (raises if the bytes decode to a non-point)."""
    out = st_geomFromWKB(wkb)

    def check(g):
        if not isinstance(g, Point):
            raise ValueError(
                f"st_pointFromWKB decoded a {type(g).__name__}"
            )
        return g

    if isinstance(out, Geometry):
        return check(out)
    return np.array([check(g) for g in out], dtype=object)


def st_castToGeometry(geom):
    """Identity upcast (the reference narrows Spark UDT types; our
    geometry columns are already dynamically typed)."""
    return geom


def st_byteArray(s):
    """String -> UTF-8 bytes (ref utility cast)."""
    if isinstance(s, (bytes, bytearray)):
        return bytes(s)
    if isinstance(s, str):
        return s.encode("utf-8")
    return np.array([st_byteArray(v) for v in s], dtype=object)


def st_polygon(line):
    """Closed LineString -> Polygon (ref st_polygon constructor)."""

    def one(g):
        if not isinstance(g, LineString):
            raise ValueError("st_polygon expects a LineString")
        c = np.asarray(g.coords, np.float64)
        if len(c) < 4 or not np.array_equal(c[0], c[-1]):
            raise ValueError("st_polygon needs a closed ring (>= 4 points)")
        return Polygon(c)

    return _scalar_or_col(line, one)


# -- additional accessors ----------------------------------------------------


def st_boundary(geom):
    """Topological boundary: polygon -> its rings as (Multi)LineString,
    linestring -> its endpoints as MultiPoint (empty when closed),
    point -> empty GeometryCollection (represented as an empty
    MultiPoint — the closest thing in this model)."""

    def one(g):
        if isinstance(g, Polygon):
            rings = [LineString(r) for r in g.rings()]
            return rings[0] if len(rings) == 1 else MultiLineString(
                tuple(rings)
            )
        if isinstance(g, MultiPolygon):
            rings = [
                LineString(r) for p in g.polygons for r in p.rings()
            ]
            return MultiLineString(tuple(rings))
        if isinstance(g, LineString):
            c = np.asarray(g.coords)
            if np.array_equal(c[0], c[-1]):
                return MultiPoint(np.empty((0, 2)))
            return MultiPoint(np.stack([c[0], c[-1]]))
        if isinstance(g, MultiLineString):
            pts = [
                p
                for l in g.lines
                for p in (
                    []
                    if np.array_equal(l.coords[0], l.coords[-1])
                    else [l.coords[0], l.coords[-1]]
                )
            ]
            return MultiPoint(
                np.stack(pts) if pts else np.empty((0, 2))
            )
        return MultiPoint(np.empty((0, 2)))  # points: empty boundary

    return _scalar_or_col(geom, one)


def _segments_self_intersect(c: np.ndarray) -> bool:
    """Any non-adjacent segment pair of the path ``c`` crosses (shared
    ring endpoints excluded)."""
    n = len(c) - 1
    if n < 2:
        return False
    a, b = c[:-1], c[1:]
    closed = np.array_equal(c[0], c[-1])
    for i in range(n - 1):
        js = np.arange(i + 2, n)
        if closed and i == 0 and len(js):
            js = js[:-1]  # last segment is adjacent to the first
        if len(js) == 0:
            continue
        p, r = a[i], b[i] - a[i]
        q, s = a[js], b[js] - a[js]
        rxs = r[0] * (s[:, 1]) - r[1] * (s[:, 0])
        qp = q - p
        t_num = qp[:, 0] * s[:, 1] - qp[:, 1] * s[:, 0]
        u_num = qp[:, 0] * r[1] - qp[:, 1] * r[0]
        with np.errstate(divide="ignore", invalid="ignore"):
            t = t_num / rxs
            u = u_num / rxs
        hit = (
            (rxs != 0)
            & (t > 1e-12) & (t < 1 - 1e-12)
            & (u > 1e-12) & (u < 1 - 1e-12)
        )
        if bool(hit.any()):
            return True
    return False


def st_isSimple(geom):
    """No self-intersection (points/multipoints are always simple;
    linestrings and polygon rings are checked pairwise)."""

    def one(g):
        if isinstance(g, (Point, MultiPoint)):
            return True
        if isinstance(g, LineString):
            return not _segments_self_intersect(np.asarray(g.coords))
        if isinstance(g, MultiLineString):
            return all(one(l) for l in g.lines)
        if isinstance(g, Polygon):
            return not any(
                _segments_self_intersect(np.asarray(r)) for r in g.rings()
            )
        if isinstance(g, MultiPolygon):
            return all(one(p) for p in g.polygons)
        return True

    out = _scalar_or_col(geom, one)
    return np.asarray(out, dtype=bool) if not isinstance(out, bool) else out


def st_isValid(geom):
    """Structural validity: rings closed with >= 4 points and simple
    (no self-intersection); lines need >= 2 points. A light version of
    the reference's JTS IsValidOp (no nested-hole topology checks)."""

    def one(g):
        if isinstance(g, Polygon):
            for r in g.rings():
                c = np.asarray(r)
                if len(c) < 4 or not np.array_equal(c[0], c[-1]):
                    return False
                if _segments_self_intersect(c):
                    return False
            return True
        if isinstance(g, MultiPolygon):
            return all(one(p) for p in g.polygons)
        if isinstance(g, LineString):
            return len(g.coords) >= 2
        if isinstance(g, MultiLineString):
            return all(len(l.coords) >= 2 for l in g.lines)
        return True

    out = _scalar_or_col(geom, one)
    return np.asarray(out, dtype=bool) if not isinstance(out, bool) else out


# -- spheroid measures (WGS84 Vincenty) --------------------------------------

_WGS84_A = 6_378_137.0
_WGS84_B = 6_356_752.314245
_WGS84_F = 1.0 / 298.257223563


def _vincenty_m(lon1, lat1, lon2, lat2) -> np.ndarray:
    """Vectorized Vincenty inverse distance (meters) on WGS84; falls back
    to the haversine-sphere value for the rare non-converging antipodal
    pairs."""
    lon1, lat1, lon2, lat2 = (
        np.asarray(v, np.float64) for v in (lon1, lat1, lon2, lat2)
    )
    U1 = np.arctan((1 - _WGS84_F) * np.tan(np.radians(lat1)))
    U2 = np.arctan((1 - _WGS84_F) * np.tan(np.radians(lat2)))
    L = np.radians(lon2 - lon1)
    lam = L.copy()
    sinU1, cosU1 = np.sin(U1), np.cos(U1)
    sinU2, cosU2 = np.sin(U2), np.cos(U2)
    sin_sig = cos_sig = sig = cos_sq_al = cos2sm = np.zeros_like(L)
    lam_prev = lam
    for _ in range(24):
        lam_prev = lam
        sin_lam, cos_lam = np.sin(lam), np.cos(lam)
        sin_sig = np.sqrt(
            (cosU2 * sin_lam) ** 2
            + (cosU1 * sinU2 - sinU1 * cosU2 * cos_lam) ** 2
        )
        cos_sig = sinU1 * sinU2 + cosU1 * cosU2 * cos_lam
        sig = np.arctan2(sin_sig, cos_sig)
        with np.errstate(divide="ignore", invalid="ignore"):
            sin_al = np.where(
                sin_sig != 0, cosU1 * cosU2 * sin_lam / sin_sig, 0.0
            )
        cos_sq_al = 1 - sin_al**2
        with np.errstate(divide="ignore", invalid="ignore"):
            cos2sm = np.where(
                cos_sq_al != 0,
                cos_sig - 2 * sinU1 * sinU2 / np.where(
                    cos_sq_al == 0, 1.0, cos_sq_al
                ),
                0.0,
            )
        C = _WGS84_F / 16 * cos_sq_al * (
            4 + _WGS84_F * (4 - 3 * cos_sq_al)
        )
        lam = L + (1 - C) * _WGS84_F * sin_al * (
            sig
            + C * sin_sig * (cos2sm + C * cos_sig * (-1 + 2 * cos2sm**2))
        )
    u_sq = cos_sq_al * (_WGS84_A**2 - _WGS84_B**2) / _WGS84_B**2
    A = 1 + u_sq / 16384 * (
        4096 + u_sq * (-768 + u_sq * (320 - 175 * u_sq))
    )
    B = u_sq / 1024 * (256 + u_sq * (-128 + u_sq * (74 - 47 * u_sq)))
    d_sig = B * sin_sig * (
        cos2sm
        + B / 4 * (
            cos_sig * (-1 + 2 * cos2sm**2)
            - B / 6 * cos2sm * (-3 + 4 * sin_sig**2) * (-3 + 4 * cos2sm**2)
        )
    )
    out = _WGS84_B * A * (sig - d_sig)
    # Vincenty's lambda iteration fails to converge for near-antipodal
    # pairs (it oscillates); substitute the haversine value on the WGS84
    # mean-radius sphere there, as the docstring promises. 1e-12 rad of
    # lambda movement ~ 6 um on the equator.
    converged = np.abs(lam - lam_prev) < 1e-12
    if not np.all(converged):
        r_mean = (2 * _WGS84_A + _WGS84_B) / 3
        p1, p2 = np.radians(lat1), np.radians(lat2)
        dp, dl = p2 - p1, np.radians(lon2 - lon1)
        h = (
            np.sin(dp / 2) ** 2
            + np.cos(p1) * np.cos(p2) * np.sin(dl / 2) ** 2
        )
        hav = 2 * r_mean * np.arcsin(np.minimum(1.0, np.sqrt(h)))
        out = np.where(converged, out, hav)
    # coincident points: exactly zero (the iteration above is stable there)
    return np.where((lon1 == lon2) & (lat1 == lat2), 0.0, out)


def st_distanceSpheroid(a, b):
    """Point-to-point distance in meters on the WGS84 spheroid (Vincenty
    inverse; the reference delegates to GeodeticCalculator)."""

    def coords(g):
        if isinstance(g, Point):
            return np.array([[g.x, g.y]])
        if _is_point_col(g):
            return g
        return np.stack([[p.x, p.y] for p in g])

    ca, cb = coords(a), coords(b)
    n = max(len(ca), len(cb))
    ca = np.broadcast_to(ca, (n, 2))
    cb = np.broadcast_to(cb, (n, 2))
    d = _vincenty_m(ca[:, 0], ca[:, 1], cb[:, 0], cb[:, 1])
    if isinstance(a, Point) and isinstance(b, Point):
        return float(d[0])
    return d


def st_lengthSpheroid(geom):
    """Path length in meters on the WGS84 spheroid (per-segment Vincenty,
    summed)."""

    def one(g):
        segs = _segments_of(g)
        if len(segs) == 0:
            return 0.0
        return float(
            _vincenty_m(
                segs[:, 0], segs[:, 1], segs[:, 2], segs[:, 3]
            ).sum()
        )

    return _scalar_or_col(geom, one)


# -- affine transforms -------------------------------------------------------


def st_rotate(geom, angle_rad: float):
    """Rotate about the origin by ``angle_rad`` (counter-clockwise)."""
    c, s = float(np.cos(angle_rad)), float(np.sin(angle_rad))
    rot = np.array([[c, s], [-s, c]])

    def one(g):
        return _map_coords(g, lambda xy: xy @ rot)

    return _scalar_or_col(geom, one)


def st_scale(geom, xf: float, yf: float):
    """Scale about the origin by (xf, yf)."""
    f = np.array([xf, yf], np.float64)

    def one(g):
        return _map_coords(g, lambda xy: xy * f)

    return _scalar_or_col(geom, one)


# -- CRS transforms and bearings ---------------------------------------------

_WEB_MERCATOR_R = 6_378_137.0
_MERC_MAX_LAT = 85.051128779806604  # atan(sinh(pi)) in degrees


def _merc_fwd(xy: np.ndarray) -> np.ndarray:
    lon = np.radians(xy[:, 0])
    lat = np.radians(np.clip(xy[:, 1], -_MERC_MAX_LAT, _MERC_MAX_LAT))
    return np.stack(
        [
            _WEB_MERCATOR_R * lon,
            _WEB_MERCATOR_R * np.log(np.tan(np.pi / 4 + lat / 2)),
        ],
        axis=1,
    )


def _merc_inv(xy: np.ndarray) -> np.ndarray:
    lon = np.degrees(xy[:, 0] / _WEB_MERCATOR_R)
    lat = np.degrees(
        2 * np.arctan(np.exp(xy[:, 1] / _WEB_MERCATOR_R)) - np.pi / 2
    )
    return np.stack([lon, lat], axis=1)


# -- WGS84 UTM (transverse Mercator, Krueger series; ref GeoTools reaches
# these through PROJ — here they are the exact flattening-series forms
# (Karney 2011), accurate to sub-mm inside a zone) ---------------------------

_UTM_K0 = 0.9996
_UTM_FE = 500_000.0
_UTM_FN_SOUTH = 10_000_000.0
_TM_N = _WGS84_F / (2.0 - _WGS84_F)


def _tm_consts():
    n = _TM_N
    n2, n3, n4, n5, n6 = n**2, n**3, n**4, n**5, n**6
    A = _WGS84_A / (1 + n) * (1 + n2 / 4 + n4 / 64 + n6 / 256)
    alpha = (
        n / 2 - 2 * n2 / 3 + 5 * n3 / 16 + 41 * n4 / 180
        - 127 * n5 / 288 + 7891 * n6 / 37800,
        13 * n2 / 48 - 3 * n3 / 5 + 557 * n4 / 1440 + 281 * n5 / 630
        - 1983433 * n6 / 1935360,
        61 * n3 / 240 - 103 * n4 / 140 + 15061 * n5 / 26880
        + 167603 * n6 / 181440,
        49561 * n4 / 161280 - 179 * n5 / 168 + 6601661 * n6 / 7257600,
        34729 * n5 / 80640 - 3418889 * n6 / 1995840,
        212378941 * n6 / 319334400,
    )
    beta = (
        n / 2 - 2 * n2 / 3 + 37 * n3 / 96 - n4 / 360 - 81 * n5 / 512
        + 96199 * n6 / 604800,
        n2 / 48 + n3 / 15 - 437 * n4 / 1440 + 46 * n5 / 105
        - 1118711 * n6 / 3870720,
        17 * n3 / 480 - 37 * n4 / 840 - 209 * n5 / 4480
        + 5569 * n6 / 90720,
        4397 * n4 / 161280 - 11 * n5 / 504 - 830251 * n6 / 7257600,
        4583 * n5 / 161280 - 108847 * n6 / 3991680,
        20648693 * n6 / 638668800,
    )
    return A, alpha, beta


_TM_A, _TM_ALPHA, _TM_BETA = _tm_consts()
_TM_E = np.sqrt(_WGS84_F * (2.0 - _WGS84_F))  # first eccentricity


def _utm_fwd(xy: np.ndarray, zone: int, south: bool) -> np.ndarray:
    lon0 = np.radians(zone * 6.0 - 183.0)
    lam = np.radians(xy[:, 0]) - lon0
    # wrap into (-pi, pi] so e.g. lon 179 vs zone 60 (177E) is a small
    # negative offset, then enforce the series' validity domain: beyond
    # ~+-45 deg from the central meridian the Krueger series diverges
    # (arctanh blows up at 90 deg) — raise, never misproject silently
    lam = np.mod(lam + np.pi, 2 * np.pi) - np.pi
    if len(lam) and float(np.abs(lam).max()) > np.radians(45.0):
        raise ValueError(
            f"point(s) more than 45 deg of longitude from UTM zone "
            f"{zone}'s central meridian: outside the projection's "
            "validity domain"
        )
    phi = np.radians(xy[:, 1])
    e = _TM_E
    s = np.sin(phi)
    t = np.sinh(np.arctanh(s) - e * np.arctanh(e * s))
    xi = np.arctan2(t, np.cos(lam))
    eta = np.arctanh(np.sin(lam) / np.sqrt(1 + t * t))
    x, y = eta.copy(), xi.copy()
    for j, a in enumerate(_TM_ALPHA, start=1):
        y += a * np.sin(2 * j * xi) * np.cosh(2 * j * eta)
        x += a * np.cos(2 * j * xi) * np.sinh(2 * j * eta)
    E = _UTM_FE + _UTM_K0 * _TM_A * x
    N = (_UTM_FN_SOUTH if south else 0.0) + _UTM_K0 * _TM_A * y
    return np.stack([E, N], axis=1)


def _utm_inv(xy: np.ndarray, zone: int, south: bool) -> np.ndarray:
    lon0 = np.radians(zone * 6.0 - 183.0)
    xi = (xy[:, 1] - (_UTM_FN_SOUTH if south else 0.0)) / (
        _UTM_K0 * _TM_A
    )
    eta = (xy[:, 0] - _UTM_FE) / (_UTM_K0 * _TM_A)
    xi_p, eta_p = xi.copy(), eta.copy()
    for j, b in enumerate(_TM_BETA, start=1):
        xi_p -= b * np.sin(2 * j * xi) * np.cosh(2 * j * eta)
        eta_p -= b * np.cos(2 * j * xi) * np.sinh(2 * j * eta)
    sh, c = np.sinh(eta_p), np.cos(xi_p)
    lam = np.arctan2(sh, c)
    tau_p = np.sin(xi_p) / np.sqrt(sh * sh + c * c)
    # invert the conformal-latitude relation by Newton on tau = tan(phi)
    # (Karney's method; 3 iterations reach float64 round-off)
    e = _TM_E
    tau = tau_p / (1.0 - e * e)
    for _ in range(3):
        sig = np.sinh(
            e * np.arctanh(e * tau / np.sqrt(1 + tau * tau))
        )
        f_tau = (
            tau * np.sqrt(1 + sig * sig)
            - sig * np.sqrt(1 + tau * tau)
            - tau_p
        )
        d_tau = (
            np.sqrt((1 + sig * sig) * (1 + tau * tau))
            - sig * tau
        ) * (1 - e * e) / (1 + (1 - e * e) * tau * tau) * np.sqrt(
            1 + tau * tau
        )
        tau = tau - f_tau / d_tau
    phi = np.arctan(tau)
    # wrap into (-180, 180]: a zone near the antimeridian otherwise
    # returns e.g. lon 185 and breaks the 4326 roundtrip
    lon = np.degrees(lam + lon0)
    lon = np.mod(lon + 180.0, 360.0) - 180.0
    return np.stack([lon, np.degrees(phi)], axis=1)


def st_transform(geom, from_crs: str, to_crs: str):
    """Reproject between EPSG:4326 (lon/lat degrees), EPSG:3857
    (spherical web mercator meters — every tiled map client), and the
    WGS84 UTM zones (EPSG:326xx north / 327xx south, exact Krueger
    flattening series). Other CRS raise loudly (this framework indexes
    in 4326; full PROJ-style pipelines are out of scope). Mercator
    latitudes clamp to the tiling domain (±85.05113°); pairs that
    involve both 3857 and UTM compose through 4326."""

    def norm(c):
        c = str(c).upper().replace("EPSG:", "")
        if c in ("4326", "CRS84"):
            return "4326"
        if c in ("3857", "900913", "102100"):
            return "3857"
        if len(c) == 5 and c[:3] in ("326", "327") and c[3:].isdigit():
            zone = int(c[3:])
            if 1 <= zone <= 60:
                return c
        raise ValueError(
            f"unsupported CRS {c!r} (4326, 3857, UTM 326xx/327xx only)"
        )

    f, t = norm(from_crs), norm(to_crs)
    if f == t:
        return geom

    def step(code, forward):
        """4326 -> code when forward else code -> 4326."""
        if code == "3857":
            return _merc_fwd if forward else _merc_inv
        zone, south = int(code[3:]), code[:3] == "327"
        if forward:
            return lambda xy: _utm_fwd(xy, zone, south)
        return lambda xy: _utm_inv(xy, zone, south)

    chain = []
    if f != "4326":
        chain.append(step(f, forward=False))
    if t != "4326":
        chain.append(step(t, forward=True))

    def fn(xy):
        for s in chain:
            xy = s(xy)
        return xy

    if _is_point_col(geom):
        return fn(np.asarray(geom, np.float64))

    def one(g):
        return _map_coords(g, lambda xy: fn(np.atleast_2d(xy)))

    return _scalar_or_col(geom, one)


def st_azimuth(a, b):
    """Bearing from point a to point b in radians clockwise from north,
    in [0, 2π) — planar on lon/lat (the reference's JTS Angle-based
    azimuth), NaN for coincident points."""

    def coords(g):
        if isinstance(g, Point):
            return np.array([[g.x, g.y]])
        if _is_point_col(g):
            return np.asarray(g, np.float64)
        return np.stack([[p.x, p.y] for p in g])

    ca, cb = coords(a), coords(b)
    n = max(len(ca), len(cb))
    ca = np.broadcast_to(ca, (n, 2))
    cb = np.broadcast_to(cb, (n, 2))
    dx = cb[:, 0] - ca[:, 0]
    dy = cb[:, 1] - ca[:, 1]
    az = np.mod(np.arctan2(dx, dy), 2 * np.pi)
    az = np.where((dx == 0) & (dy == 0), np.nan, az)
    if isinstance(a, Point) and isinstance(b, Point):
        return float(az[0])
    return az


# -- polygon boolean ops (geom/clip.py Greiner-Hormann engine) ---------------


def _boolean_op(a, b, fn):
    if isinstance(a, Geometry) and isinstance(b, Geometry):
        return fn(a, b)
    if isinstance(a, Geometry):
        return np.array([fn(a, g) for g in b], dtype=object)
    if isinstance(b, Geometry):
        return np.array([fn(g, b) for g in a], dtype=object)
    return np.array([fn(x, y) for x, y in zip(a, b)], dtype=object)


def st_intersection(a, b):
    """Polygon ∩ polygon (holes supported on either side; see
    geom/clip.py for the contract)."""
    from geomesa_tpu.geom.clip import polygon_intersection

    return _boolean_op(a, b, polygon_intersection)


def st_union(a, b):
    from geomesa_tpu.geom.clip import polygon_union

    return _boolean_op(a, b, polygon_union)


def st_difference(a, b):
    from geomesa_tpu.geom.clip import polygon_difference

    return _boolean_op(a, b, polygon_difference)


def st_symDifference(a, b):
    from geomesa_tpu.geom.clip import polygon_sym_difference

    return _boolean_op(a, b, polygon_sym_difference)


def st_aggregateIntersection(geoms):
    """Fold ∩ over a geometry column (ref aggregate UDF)."""
    from geomesa_tpu.geom.clip import polygon_intersection

    geoms = list(geoms)
    if not geoms:
        return MultiPolygon(())
    acc = geoms[0]
    for g in geoms[1:]:
        acc = polygon_intersection(acc, g)
    return acc


def st_aggregateUnion(geoms):
    """Fold ∪ over a geometry column (ref aggregate UDF)."""
    from geomesa_tpu.geom.clip import polygon_union

    geoms = list(geoms)
    if not geoms:
        return MultiPolygon(())
    acc = geoms[0]
    for g in geoms[1:]:
        acc = polygon_union(acc, g)
    return acc


# -- registry ----------------------------------------------------------------

FUNCTIONS = {
    name: fn
    for name, fn in list(globals().items())
    if name.startswith("st_") and callable(fn)
}

__all__ = sorted(FUNCTIONS)
