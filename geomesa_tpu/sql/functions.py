"""Vectorized ``st_*`` spatial functions (ref: geomesa-spark-sql
GeometricConstructorFunctions / GeometricAccessorFunctions /
SpatialRelationFunctions / GeometricProcessingFunctions [UNVERIFIED -
empty reference mount]).

Conventions:
- A *point column* is an (n, 2) float64 array; a *geometry column* is an
  object array of geom.base Geometry; a scalar Geometry broadcasts.
- Relations return bool arrays (or bool for scalar/scalar).
- Names and argument order mirror the reference's Spark UDFs
  (``st_contains(a, b)`` = a contains b).
"""

from __future__ import annotations

import numpy as np

from geomesa_tpu.geom.base import (
    Envelope,
    Geometry,
    LineString,
    MultiLineString,
    MultiPoint,
    MultiPolygon,
    Point,
    Polygon,
)
from geomesa_tpu.geom.predicates import (
    geometry_intersects,
    geometry_within,
    points_in_polygon,
)

EARTH_RADIUS_M = 6_371_008.8


# -- constructors ------------------------------------------------------------


def st_point(x, y):
    """(x, y) columns -> point column; scalars -> Point."""
    if np.isscalar(x) and np.isscalar(y):
        return Point(float(x), float(y))
    return np.stack(
        [np.asarray(x, np.float64), np.asarray(y, np.float64)], axis=1
    )


def st_makeBBOX(xmin: float, ymin: float, xmax: float, ymax: float) -> Polygon:
    return Polygon(
        np.array(
            [
                (xmin, ymin),
                (xmax, ymin),
                (xmax, ymax),
                (xmin, ymax),
                (xmin, ymin),
            ],
            dtype=np.float64,
        )
    )


def st_geomFromWKT(wkt):
    from geomesa_tpu.geom.wkt import parse_wkt

    if isinstance(wkt, str):
        return parse_wkt(wkt)
    return np.array([parse_wkt(w) for w in wkt], dtype=object)


def st_geomFromWKB(wkb):
    from geomesa_tpu.geom.wkb import from_wkb

    if isinstance(wkb, (bytes, bytearray)):
        return from_wkb(bytes(wkb))
    return np.array([from_wkb(bytes(w)) for w in wkb], dtype=object)


# -- accessors ---------------------------------------------------------------


def _is_point_col(col) -> bool:
    return (
        isinstance(col, np.ndarray) and col.dtype != object and col.ndim == 2
    )


def st_x(geom):
    if isinstance(geom, Point):
        return geom.x
    if _is_point_col(geom):
        return np.ascontiguousarray(geom[:, 0])
    return np.array(
        [g.x if isinstance(g, Point) else np.nan for g in geom]
    )


def st_y(geom):
    if isinstance(geom, Point):
        return geom.y
    if _is_point_col(geom):
        return np.ascontiguousarray(geom[:, 1])
    return np.array(
        [g.y if isinstance(g, Point) else np.nan for g in geom]
    )


def st_envelope(geom):
    """Envelope (or array of Envelope) of geometries."""
    if isinstance(geom, Geometry):
        return geom.envelope
    if _is_point_col(geom):
        return np.array(
            [Envelope(x, y, x, y) for x, y in geom], dtype=object
        )
    return np.array([g.envelope for g in geom], dtype=object)


def _ring_area(r: np.ndarray) -> float:
    x, y = r[:, 0], r[:, 1]
    return 0.5 * float(np.sum(x[:-1] * y[1:] - x[1:] * y[:-1]))


def _geom_area(g) -> float:
    if isinstance(g, Polygon):
        shell = abs(_ring_area(g.shell))
        return shell - sum(abs(_ring_area(h)) for h in g.holes)
    if isinstance(g, MultiPolygon):
        return sum(_geom_area(p) for p in g.polygons)
    return 0.0


def st_area(geom):
    if isinstance(geom, Geometry):
        return _geom_area(geom)
    if _is_point_col(geom):
        return np.zeros(len(geom))
    return np.array([_geom_area(g) for g in geom])


def _geom_length(g) -> float:
    if isinstance(g, LineString):
        d = np.diff(g.coords, axis=0)
        return float(np.hypot(d[:, 0], d[:, 1]).sum())
    if isinstance(g, MultiLineString):
        return sum(_geom_length(l) for l in g.lines)
    if isinstance(g, Polygon):
        return sum(
            float(np.hypot(*np.diff(r, axis=0).T).sum()) for r in g.rings()
        )
    if isinstance(g, MultiPolygon):
        return sum(_geom_length(p) for p in g.polygons)
    return 0.0


def st_length(geom):
    if isinstance(geom, Geometry):
        return _geom_length(geom)
    if _is_point_col(geom):
        return np.zeros(len(geom))
    return np.array([_geom_length(g) for g in geom])


def _geom_centroid(g) -> Point:
    if isinstance(g, Point):
        return g
    vs = _all_vertices(g)
    return Point(float(vs[:, 0].mean()), float(vs[:, 1].mean()))


def _all_vertices(g) -> np.ndarray:
    if isinstance(g, Point):
        return np.array([[g.x, g.y]])
    if isinstance(g, LineString):
        return g.coords
    if isinstance(g, Polygon):
        return g.shell[:-1]
    if isinstance(g, MultiPoint):
        return np.array([[p.x, p.y] for p in g.points])
    if isinstance(g, MultiLineString):
        return np.concatenate([l.coords for l in g.lines])
    if isinstance(g, MultiPolygon):
        return np.concatenate([p.shell[:-1] for p in g.polygons])
    raise TypeError(type(g))


def st_centroid(geom):
    if isinstance(geom, Geometry):
        return _geom_centroid(geom)
    if _is_point_col(geom):
        return geom.copy()
    return np.array([_geom_centroid(g) for g in geom], dtype=object)


def st_numPoints(geom):
    def n(g):
        return len(_all_vertices(g)) if not isinstance(g, Point) else 1

    if isinstance(geom, Geometry):
        return n(geom)
    if _is_point_col(geom):
        return np.ones(len(geom), dtype=np.int64)
    return np.array([n(g) for g in geom], dtype=np.int64)


def st_bufferPoint(geom, distance_m: float, segments: int = 32):
    """Geodesic-ish circular buffer around point(s) in meters (ref
    st_bufferPoint: degrees-from-meters at the point's latitude)."""

    def circle(x, y):
        dlat = np.degrees(distance_m / EARTH_RADIUS_M)
        dlon = dlat / max(np.cos(np.radians(y)), 1e-9)
        t = np.linspace(0.0, 2 * np.pi, segments + 1)
        ring = np.stack(
            [x + dlon * np.cos(t), y + dlat * np.sin(t)], axis=1
        )
        ring[-1] = ring[0]
        return Polygon(ring)

    if isinstance(geom, Point):
        return circle(geom.x, geom.y)
    if _is_point_col(geom):
        return np.array([circle(x, y) for x, y in geom], dtype=object)
    return np.array(
        [circle(g.x, g.y) for g in geom], dtype=object
    )


# -- relations ---------------------------------------------------------------


def _as_geom_scalar(g):
    return g if isinstance(g, Geometry) else None


def _pairwise(a, b, fn, point_fast=None):
    """Broadcast a relation over (column, scalar), (scalar, column),
    (column, column) or (scalar, scalar) inputs."""
    a_scalar = isinstance(a, Geometry)
    b_scalar = isinstance(b, Geometry)
    if a_scalar and b_scalar:
        return fn(a, b)
    if _is_point_col(a) and b_scalar and point_fast is not None:
        return point_fast(a, b, False)
    if a_scalar and _is_point_col(b) and point_fast is not None:
        return point_fast(b, a, True)
    av = a if not a_scalar else None
    bv = b if not b_scalar else None
    n = len(av) if av is not None else len(bv)
    out = np.empty(n, dtype=bool)
    for i in range(n):
        ga = a if a_scalar else _row_geom(a, i)
        gb = b if b_scalar else _row_geom(b, i)
        out[i] = fn(ga, gb)
    return out


def _row_geom(col, i):
    if _is_point_col(col):
        return Point(float(col[i, 0]), float(col[i, 1]))
    return col[i]


def _points_vs_geom_intersects(pts: np.ndarray, g: Geometry, flipped: bool):
    # symmetric relation: ignore flipped
    if isinstance(g, (Polygon, MultiPolygon)):
        x, y = pts[:, 0], pts[:, 1]
        if isinstance(g, Polygon):
            return points_in_polygon(x, y, g.rings())
        m = np.zeros(len(pts), dtype=bool)
        for p in g.polygons:
            m |= points_in_polygon(x, y, p.rings())
        return m
    out = np.empty(len(pts), dtype=bool)
    for i in range(len(pts)):
        out[i] = geometry_intersects(
            Point(float(pts[i, 0]), float(pts[i, 1])), g
        )
    return out


def st_intersects(a, b):
    return _pairwise(
        a, b, geometry_intersects, point_fast=_points_vs_geom_intersects
    )


def st_disjoint(a, b):
    r = st_intersects(a, b)
    return ~r if isinstance(r, np.ndarray) else not r


def st_contains(a, b):
    """a contains b (b within a)."""

    def fn(ga, gb):
        return geometry_within(gb, ga)

    def pf(pts, g, flipped):
        if flipped:
            # pts contains g: a point only contains an equal point
            if isinstance(g, Point):
                return (pts[:, 0] == g.x) & (pts[:, 1] == g.y)
            return np.zeros(len(pts), dtype=bool)
        return _points_vs_geom_intersects(pts, g, False) if isinstance(
            g, (Polygon, MultiPolygon)
        ) else np.array(
            [fn(_row_geom(pts, i), g) for i in range(len(pts))]
        )

    # st_contains(scalar_geom, point_col): the common pushdown shape
    if isinstance(a, Geometry) and not isinstance(b, Geometry):
        if _is_point_col(b):
            return pf(b, a, False)
        return np.array([fn(a, gb) for gb in b], dtype=bool)
    if isinstance(b, Geometry) and not isinstance(a, Geometry):
        if _is_point_col(a):
            return pf(a, b, True)
        return np.array([fn(ga, b) for ga in a], dtype=bool)
    return _pairwise(a, b, fn)


def st_within(a, b):
    """a within b."""
    return st_contains(b, a)


def _segments_of(g) -> np.ndarray:
    """(m, 4) [x0 y0 x1 y1] edge list (rings include holes, via the shared
    predicates helper); point-like geometries yield zero-length segments so
    one distance formula covers every pair."""
    from geomesa_tpu.geom.predicates import _segments_of as _geom_segments

    segs = _geom_segments(g)
    if segs is not None:
        return segs
    va = _all_vertices(g)
    return np.concatenate([va, va], axis=1)


def pt_seg_project(pts: np.ndarray, segs: np.ndarray):
    """Clamped projection of each point onto each segment. ``pts`` is
    (n, 2), ``segs`` is (m, 4) as [x0, y0, x1, y1]. Returns ``(t, dist2)``
    with shape (n, m): the clamped parameter along each segment and the
    squared point-to-segment distance."""
    p = pts[:, None, :]
    a = segs[None, :, 0:2]
    d = segs[None, :, 2:4] - a
    len2 = (d**2).sum(-1)
    t = ((p - a) * d).sum(-1) / np.where(len2 == 0, 1.0, len2)
    t = np.clip(np.where(len2 == 0, 0.0, t), 0.0, 1.0)
    near = a + t[..., None] * d
    return t, ((p - near) ** 2).sum(-1)


def _pt_seg_dist(pts: np.ndarray, segs: np.ndarray) -> float:
    """min over all (point, segment) pairs of the exact point-to-segment
    distance (clamped projection)."""
    _, dist2 = pt_seg_project(pts, segs)
    return float(np.sqrt(dist2.min()))


def st_distance(a, b):
    """Exact planar distance: 0 when intersecting, else the minimum
    point-to-segment distance both ways (exact for non-crossing
    geometries, since any crossing pair would have intersected)."""

    def fn(ga, gb):
        if isinstance(ga, Point) and isinstance(gb, Point):
            return float(np.hypot(ga.x - gb.x, ga.y - gb.y))
        if geometry_intersects(ga, gb):
            return 0.0
        # point sets come from the segment endpoints so hole-ring vertices
        # participate (shells alone would overestimate near holes)
        sa, sb = _segments_of(ga), _segments_of(gb)
        pa = np.concatenate([sa[:, 0:2], sa[:, 2:4]], axis=0)
        pb = np.concatenate([sb[:, 0:2], sb[:, 2:4]], axis=0)
        return min(_pt_seg_dist(pa, sb), _pt_seg_dist(pb, sa))

    if isinstance(a, Geometry) and isinstance(b, Geometry):
        return fn(a, b)
    if _is_point_col(a) and isinstance(b, Point):
        return np.hypot(a[:, 0] - b.x, a[:, 1] - b.y)
    if _is_point_col(b) and isinstance(a, Point):
        return np.hypot(b[:, 0] - a.x, b[:, 1] - a.y)
    if _is_point_col(a) and _is_point_col(b):
        return np.hypot(a[:, 0] - b[:, 0], a[:, 1] - b[:, 1])
    n = len(a) if not isinstance(a, Geometry) else len(b)
    return np.array(
        [
            fn(
                a if isinstance(a, Geometry) else _row_geom(a, i),
                b if isinstance(b, Geometry) else _row_geom(b, i),
            )
            for i in range(n)
        ]
    )


def st_dwithin(a, b, distance: float):
    d = st_distance(a, b)
    return d <= distance


def st_distanceSphere(a, b):
    """Haversine great-circle distance in meters between points/point
    columns (ref st_distanceSpheroid's spherical sibling)."""

    def coords(v):
        if isinstance(v, Point):
            return np.array([v.x]), np.array([v.y])
        if _is_point_col(v):
            return v[:, 0], v[:, 1]
        return (
            np.array([g.x for g in v]),
            np.array([g.y for g in v]),
        )

    ax, ay = coords(a)
    bx, by = coords(b)
    lat1, lat2 = np.radians(ay), np.radians(by)
    dlat = lat2 - lat1
    dlon = np.radians(bx - ax)
    h = (
        np.sin(dlat / 2) ** 2
        + np.cos(lat1) * np.cos(lat2) * np.sin(dlon / 2) ** 2
    )
    d = 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(h, 0, 1)))
    if isinstance(a, Point) and isinstance(b, Point):
        return float(d[0])
    return d
