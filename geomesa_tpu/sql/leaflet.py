"""Leaflet map rendering for feature batches and density grids.

Ref role: geomesa-spark-jupyter-leaflet (the notebook visualization
module [UNVERIFIED - empty reference mount]) — render query results and
density heatmaps onto an interactive Leaflet map. Here the output is a
SELF-CONTAINED HTML document (Leaflet CSS/JS from the public CDN; all
DATA embedded inline as GeoJSON / a raw grid drawn onto a canvas image
overlay), so it works from a notebook (``IPython.display.HTML``), a file
on disk, or an HTTP response — no server round trips after load.

    from geomesa_tpu.sql.leaflet import leaflet_map, save_map
    html = leaflet_map(features=batch)                   # points/geoms
    html = leaflet_map(density=(grid, env))              # heatmap
    html = leaflet_map(features=batch, density=(g, env)) # both
    save_map("map.html", features=batch)
"""

from __future__ import annotations

import html as _html
import json

import numpy as np


def _embed_json(obj) -> str:
    """``json.dumps`` hardened for embedding inside a ``<script>`` block.

    A property value containing ``</script>`` would otherwise terminate
    the script element early (stored XSS via ingested attributes); the
    HTML parser tokenizes ``</`` inside scripts, so escaping just that
    sequence (and ``<!--`` per the WHATWG script-data rules) is
    sufficient and keeps the payload valid JSON/JS (``\\/`` and
    ``\\u003c`` are both legal JSON escapes).
    """
    return (
        json.dumps(obj)
        .replace("</", "<\\/")
        .replace("<!--", "\\u003c!--")
    )

_PAGE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"/>
<title>{title}</title>
<link rel="stylesheet"
 href="https://unpkg.com/leaflet@1.9.4/dist/leaflet.css"/>
<script src="https://unpkg.com/leaflet@1.9.4/dist/leaflet.js"></script>
<style>html,body,#map{{height:100%;margin:0}}</style>
</head><body><div id="map"></div><script>
var map = L.map('map').setView([{lat}, {lon}], {zoom});
L.tileLayer('https://{{s}}.tile.openstreetmap.org/{{z}}/{{x}}/{{y}}.png',
  {{maxZoom: 19, attribution: '&copy; OpenStreetMap'}}).addTo(map);
{density_js}
{features_js}
</script></body></html>
"""

_DENSITY_JS = """
var grid = {grid_json};
var gh = grid.length, gw = grid[0].length;
var cnv = document.createElement('canvas');
cnv.width = gw; cnv.height = gh;
var ctx = cnv.getContext('2d');
var img = ctx.createImageData(gw, gh);
var mx = 0;
for (var r = 0; r < gh; r++)
  for (var c = 0; c < gw; c++) if (grid[r][c] > mx) mx = grid[r][c];
for (var r = 0; r < gh; r++) {{
  for (var c = 0; c < gw; c++) {{
    // grid row 0 = SOUTH edge; canvas row 0 = top -> flip vertically
    var v = mx > 0 ? grid[gh - 1 - r][c] / mx : 0;
    var i = 4 * (r * gw + c);
    img.data[i] = 255;
    img.data[i + 1] = Math.round(255 * (1 - v));
    img.data[i + 2] = 0;
    img.data[i + 3] = v > 0 ? Math.round(40 + 215 * v) : 0;
  }}
}}
ctx.putImageData(img, 0, 0);
L.imageOverlay(cnv.toDataURL(), [[{ymin}, {xmin}], [{ymax}, {xmax}]],
  {{opacity: 0.7, interactive: false}}).addTo(map);
"""

_FEATURES_JS = """
var fc = {geojson};
L.geoJSON(fc, {{
  pointToLayer: function (f, latlng) {{
    return L.circleMarker(latlng,
      {{radius: 4, weight: 1, color: '#1f6feb', fillOpacity: 0.7}});
  }},
  onEachFeature: function (f, layer) {{
    if (f.properties) {{
      // Popup content is interpreted as HTML by Leaflet: escape the
      // untrusted property keys/values so ingested data can't inject
      // markup into the map page.
      var esc = function (s) {{
        return String(s).replace(/&/g, '&amp;').replace(/</g, '&lt;')
          .replace(/>/g, '&gt;').replace(/"/g, '&quot;');
      }};
      var rows = Object.entries(f.properties).map(
        function (kv) {{ return esc(kv[0]) + ': ' + esc(kv[1]); }});
      layer.bindPopup(rows.join('<br/>'));
    }}
  }}
}}).addTo(map);
"""


def _env_tuple(env):
    if hasattr(env, "xmin"):
        return float(env.xmin), float(env.ymin), float(env.xmax), float(env.ymax)
    e = [float(v) for v in env]
    return e[0], e[1], e[2], e[3]


def leaflet_map(
    features=None,
    density=None,
    center=None,
    zoom: "int | None" = None,
    max_features: int = 10_000,
    title: str = "geomesa-tpu map",
) -> str:
    """Self-contained Leaflet HTML for a FeatureBatch (or GeoJSON
    feature-collection dict) and/or a ``(grid, envelope)`` density pair.

    ``max_features`` caps the embedded GeoJSON (an interactive map with
    millions of inline markers is unusable and tens of MB; run the
    density path for full-data views). Center/zoom default to the data's
    envelope."""
    if features is None and density is None:
        raise ValueError("leaflet_map needs features= and/or density=")

    features_js = ""
    fc = None
    if features is not None:
        if isinstance(features, dict):
            fc = features
        else:
            from geomesa_tpu.export import feature_collection

            batch = features
            if len(batch) > max_features:
                batch = batch.take(np.arange(max_features))
            fc = feature_collection(batch)
        features_js = _FEATURES_JS.format(geojson=_embed_json(fc))

    density_js = ""
    denv = None
    if density is not None:
        grid, env = density
        grid = np.asarray(grid, np.float64)
        denv = _env_tuple(env)
        density_js = _DENSITY_JS.format(
            grid_json=_embed_json(
                [[round(float(v), 4) for v in row] for row in grid]
            ),
            xmin=denv[0], ymin=denv[1], xmax=denv[2], ymax=denv[3],
        )

    if center is None:
        if denv is not None:
            center = ((denv[1] + denv[3]) / 2, (denv[0] + denv[2]) / 2)
        elif fc is not None and fc.get("features"):
            xs, ys = [], []
            for f in fc["features"]:
                g = f.get("geometry") or {}
                if g.get("type") == "Point":
                    xs.append(g["coordinates"][0])
                    ys.append(g["coordinates"][1])
            center = (
                (float(np.mean(ys)), float(np.mean(xs))) if xs else (0, 0)
            )
        else:
            center = (0, 0)
    return _PAGE.format(
        title=_html.escape(title),
        lat=float(center[0]),
        lon=float(center[1]),
        zoom=int(zoom) if zoom is not None else 4,
        density_js=density_js,
        features_js=features_js,
    )


def save_map(path: str, **kwargs) -> str:
    """Write :func:`leaflet_map` output to ``path``; returns the path."""
    html = leaflet_map(**kwargs)
    with open(path, "w", encoding="utf-8") as f:
        f.write(html)
    return path
