"""Inter-process locking for shared storage roots.

Ref role: geomesa-utils ``DistributedLocking`` (ZooKeeper-backed in the
reference — [UNVERIFIED - empty reference mount]). This stack has no
ZooKeeper; the coordination scope is a shared POSIX filesystem, so the
lock is ``flock(2)`` on a sentinel file in the store root: exclusive for
destructive maintenance (compaction rewrites partition files in place),
shared for readers that must not observe a half-rewritten directory.

flock is advisory and per open-file-description: every acquisition opens
its own fd, so it works across processes AND across threads of one
process. NFS caveat (same as any flock user): requires a server with
lock support; local disks and most cluster filesystems are fine.
"""

from __future__ import annotations

import fcntl
import os
import threading
import time
from contextlib import contextmanager


class LockTimeout(TimeoutError):
    pass


def checked_lock(name: str, *, blocking_ok: bool = False):
    """The project's IN-PROCESS mutex factory: a plain ``threading.Lock``
    in production, a lock-order-checked wrapper when the
    ``GEOMESA_TPU_LOCKCHECK`` environment variable is set (see
    analysis/lockcheck.py -- ABBA cycle detection + held-across-blocking
    events; the test suite runs entirely under it). Every lock in the
    package is built here (lint rule GT001 enforces it); ``name`` is the
    node in the acquisition graph, so per-instance locks sharing a name
    collapse into one bounded node.

    ``blocking_ok=True`` declares that holding this lock across blocking
    calls is the lock's PURPOSE (append-log ordering, first-touch device
    staging) and exempts it from held-across-blocking events -- pair it
    with the reasoned ``# lint: disable=GT002(...)`` at the blocking
    site so both checkers tell the same story."""
    from geomesa_tpu.analysis import lockcheck

    if not lockcheck.enabled():
        return threading.Lock()
    lockcheck.install_probes()
    return lockcheck.CheckedLock(name, blocking_ok=blocking_ok)


def checked_rlock(name: str, *, blocking_ok: bool = False):
    """Re-entrant flavor of :func:`checked_lock` (``threading.RLock``
    drop-in; re-acquisitions by the holder record no self-edges)."""
    from geomesa_tpu.analysis import lockcheck

    if not lockcheck.enabled():
        return threading.RLock()
    lockcheck.install_probes()
    return lockcheck.CheckedLock(name, reentrant=True, blocking_ok=blocking_ok)


@contextmanager
def file_lock(
    path: str,
    *,
    shared: bool = False,
    timeout_s: float = 60.0,
    poll_s: float = 0.02,
):
    """Hold ``path`` flock'd (exclusive by default) for the with-body.
    Raises LockTimeout if another holder keeps it past ``timeout_s``.

    Exclusive holders record their pid in the sentinel file so a
    timeout can name the (last) writer holding things up; the poll
    sleeps with jitter so a fleet of starved waiters does not resync
    into lockstep probes against the holder's release window."""
    import random

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    flags = (fcntl.LOCK_SH if shared else fcntl.LOCK_EX) | fcntl.LOCK_NB
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            try:
                fcntl.flock(fd, flags)
                break
            except (BlockingIOError, InterruptedError):
                if time.monotonic() >= deadline:
                    holder = ""
                    try:
                        with open(path) as fh:
                            holder = fh.read(64).strip()
                    except OSError:
                        pass
                    held = (
                        f" (last exclusive holder: pid {holder})"
                        if holder
                        else ""
                    )
                    raise LockTimeout(
                        f"lock {path!r} not acquired within "
                        f"{timeout_s}s{held}"
                    ) from None
                time.sleep(poll_s * (1.0 + random.random()))
        if not shared:
            # debuggability only (concurrent SH holders would race a
            # write, and the pid intentionally persists after release
            # as "last holder"): never let it fail an acquisition
            try:
                os.ftruncate(fd, 0)
                os.pwrite(fd, str(os.getpid()).encode(), 0)
            except OSError:
                pass
        yield
    finally:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)
