"""Inter-process locking for shared storage roots.

Ref role: geomesa-utils ``DistributedLocking`` (ZooKeeper-backed in the
reference — [UNVERIFIED - empty reference mount]). This stack has no
ZooKeeper; the coordination scope is a shared POSIX filesystem, so the
lock is ``flock(2)`` on a sentinel file in the store root: exclusive for
destructive maintenance (compaction rewrites partition files in place),
shared for readers that must not observe a half-rewritten directory.

flock is advisory and per open-file-description: every acquisition opens
its own fd, so it works across processes AND across threads of one
process. NFS caveat (same as any flock user): requires a server with
lock support; local disks and most cluster filesystems are fine.
"""

from __future__ import annotations

import fcntl
import os
import time
from contextlib import contextmanager


class LockTimeout(TimeoutError):
    pass


@contextmanager
def file_lock(
    path: str,
    *,
    shared: bool = False,
    timeout_s: float = 60.0,
    poll_s: float = 0.02,
):
    """Hold ``path`` flock'd (exclusive by default) for the with-body.
    Raises LockTimeout if another holder keeps it past ``timeout_s``."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
    flags = (fcntl.LOCK_SH if shared else fcntl.LOCK_EX) | fcntl.LOCK_NB
    deadline = time.monotonic() + timeout_s
    try:
        while True:
            try:
                fcntl.flock(fd, flags)
                break
            except (BlockingIOError, InterruptedError):
                if time.monotonic() >= deadline:
                    raise LockTimeout(
                        f"lock {path!r} not acquired within {timeout_s}s"
                    ) from None
                time.sleep(poll_s)
        yield
    finally:
        try:
            fcntl.flock(fd, fcntl.LOCK_UN)
        finally:
            os.close(fd)
