"""Metrics registry with Prometheus text exposition (ref: geomesa-metrics
-- dropwizard/micrometer reporters, micrometer/PrometheusSetup, wired into
ingest/converters [UNVERIFIED - empty reference mount]).

Tiny dependency-free core: Counter / Gauge / Histogram(+timer) with label
support, a process-global registry, and the Prometheus text format for
scraping. Converters and store write/query paths increment these; hosts
can serve ``prometheus_text()`` from any HTTP endpoint.
"""

from __future__ import annotations

import time
from bisect import bisect_left

from geomesa_tpu.locking import checked_lock


class _Metric:
    def __init__(self, name: str, help_: str, kind: str):
        self.name = name
        self.help = help_
        self.kind = kind
        self._values: dict = {}
        self._lock = checked_lock(f"metrics.{name}")

    def labels(self, **labels) -> tuple:
        return tuple(sorted(labels.items()))


class Counter(_Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_, "counter")

    def inc(self, amount: float = 1.0, **labels) -> None:
        key = self.labels(**labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels) -> float:
        return self._values.get(self.labels(**labels), 0.0)


class Gauge(_Metric):
    def __init__(self, name, help_=""):
        super().__init__(name, help_, "gauge")

    def set(self, v: float, **labels) -> None:
        with self._lock:
            self._values[self.labels(**labels)] = float(v)

    def inc(self, amount: float = 1.0, **labels) -> None:
        """Delta update — the right form when several concurrent actors
        contribute to one gauge (each adds/removes its own share; a
        ``set`` from any one of them would clobber the others)."""
        key = self.labels(**labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def dec(self, amount: float = 1.0, **labels) -> None:
        self.inc(-amount, **labels)

    def value(self, **labels) -> float:
        return self._values.get(self.labels(**labels), 0.0)


DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0
)


class Histogram(_Metric):
    """Cumulative-bucket histogram; ``time()`` context manager included.

    ``observe(..., exemplar={"trace_id": tid})`` attaches an OpenMetrics
    exemplar to the bucket the value lands in (last writer wins): the
    exposition then links a bucket — say the p99 one — to an actual
    captured trace id, so a latency violation on ``/metrics`` resolves
    to its ``/debug/traces`` entry."""

    def __init__(self, name, help_="", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, "histogram")
        self.buckets = tuple(sorted(buckets))

    def observe(self, v: float, *, exemplar=None, **labels) -> None:
        key = self.labels(**labels)
        with self._lock:
            st = self._values.setdefault(
                key, {"counts": [0] * (len(self.buckets) + 1), "sum": 0.0, "n": 0}
            )
            # le-bucket: first bound >= v (cumulated at exposition time);
            # past the last bound lands in the trailing +Inf slot
            b = bisect_left(self.buckets, v)
            st["counts"][b] += 1
            st["sum"] += v
            st["n"] += 1
            if exemplar:
                st.setdefault("exemplars", {})[b] = (
                    dict(exemplar), float(v)
                )

    def time(self, **labels):
        return _Timer(self, labels)

    def stats(self, **labels) -> dict:
        return self._values.get(self.labels(**labels), {"counts": [], "sum": 0.0, "n": 0})


class _Timer:
    def __init__(self, hist: Histogram, labels: dict):
        self.hist = hist
        self.labels = labels

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.hist.observe(time.perf_counter() - self.t0, **self.labels)


class MetricsRegistry:
    def __init__(self):
        self._metrics: dict = {}
        self._lock = checked_lock("metrics.registry")

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get(name, lambda: Counter(name, help_), Counter)

    def gauge(self, name: str, help_: str = "") -> Gauge:
        return self._get(name, lambda: Gauge(name, help_), Gauge)

    def histogram(self, name: str, help_: str = "", buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(
            name, lambda: Histogram(name, help_, buckets), Histogram
        )

    def _get(self, name, factory, cls):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = factory()
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} is a {m.kind}")
            return m

    def prometheus_text(self, openmetrics: bool = False) -> str:
        """Prometheus exposition. Default: the classic text format
        (text/plain; version 0.0.4) — NO exemplars, because the 0.0.4
        parser rejects anything but an optional timestamp after the
        value and one suffixed line would fail the WHOLE scrape.
        ``openmetrics=True`` (the server sets it when the scraper's
        Accept header negotiates application/openmetrics-text) emits
        the OpenMetrics form: exemplar suffixes on histogram buckets
        and the terminating ``# EOF``.

        Every mutable structure is SNAPSHOTTED under its metric's lock
        before formatting: writers mutate ``_values`` (and histogram
        ``counts`` lists) concurrently on serving threads, and iterating
        them live can raise mid-scrape or emit a histogram whose buckets
        disagree with its count."""
        with self._lock:
            metrics = sorted(self._metrics.items())
        lines: list = []
        for name, m in metrics:
            if m.help:
                lines.append(f"# HELP {name} {m.help}")
            lines.append(f"# TYPE {name} {m.kind}")
            if isinstance(m, (Counter, Gauge)):
                with m._lock:
                    values = sorted(m._values.items())
                for key, v in values:
                    lines.append(f"{name}{_fmt_labels(key)} {_fmt_val(v)}")
            else:
                with m._lock:
                    stats = sorted(
                        (
                            key, list(st["counts"]), st["sum"], st["n"],
                            dict(st.get("exemplars", ())),
                        )
                        for key, st in m._values.items()
                    )
                for key, counts, total, n, exemplars in stats:
                    cum = 0
                    for i, (b, c) in enumerate(
                        zip(m.buckets + (float("inf"),), counts)
                    ):
                        cum += c
                        lb = "+Inf" if b == float("inf") else _fmt_val(b)
                        line = (
                            f"{name}_bucket"
                            f"{_fmt_labels(key + (('le', lb),))} {cum}"
                        )
                        ex = exemplars.get(i) if openmetrics else None
                        if ex is not None:
                            # OpenMetrics exemplar: "<line> # {labels} value"
                            line += (
                                f" # {_fmt_labels(tuple(sorted(ex[0].items())))}"
                                f" {_fmt_val(ex[1])}"
                            )
                        lines.append(line)
                    lines.append(f"{name}_sum{_fmt_labels(key)} {_fmt_val(total)}")
                    lines.append(f"{name}_count{_fmt_labels(key)} {n}")
        if openmetrics:
            lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()


def _esc_label(v) -> str:
    """Prometheus-spec label value escaping (backslash, double quote,
    newline) — a filter string carried in a label must not be able to
    break out of its quotes or split the exposition line."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(key) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_esc_label(v)}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_val(v: float) -> str:
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


REGISTRY = MetricsRegistry()

# canonical framework metrics (ref instruments converters + ingest)
features_ingested = REGISTRY.counter(
    "geomesa_features_ingested_total", "features written to stores"
)
features_failed = REGISTRY.counter(
    "geomesa_convert_failures_total", "converter records failed"
)
queries_run = REGISTRY.counter(
    "geomesa_queries_total", "queries executed"
)
query_seconds = REGISTRY.histogram(
    "geomesa_query_duration_seconds", "end-to-end query latency"
)

# device query scheduler (geomesa_tpu/sched): the serving path's
# admission/fusion observability — queue pressure, wait time, fusion
# factor (sched_queries_total / sched_launches_total), and shed load
sched_queue_depth = REGISTRY.gauge(
    "geomesa_sched_queue_depth", "requests waiting in the scheduler queue"
)
sched_queries = REGISTRY.counter(
    "geomesa_sched_queries_total", "requests executed by the scheduler"
)
sched_launches = REGISTRY.counter(
    "geomesa_sched_launches_total", "device scan launches dispatched"
)
sched_fused = REGISTRY.counter(
    "geomesa_sched_fused_queries_total",
    "queries answered by a shared (fused) device launch",
)
sched_rejected = REGISTRY.counter(
    "geomesa_sched_rejections_total", "requests rejected at admission"
)
sched_expired = REGISTRY.counter(
    "geomesa_sched_deadline_expired_total",
    "requests that expired before or during execution",
)
sched_wait_seconds = REGISTRY.histogram(
    "geomesa_sched_wait_seconds", "queue wait before execution"
)

# host-I/O prefetch pipeline (store/prefetch.py): where the out-of-core
# scan / FS staging / bulk ingest host time goes (read vs decode vs
# stage), how deep the read-ahead runs, and queue occupancy in bytes
io_read_seconds = REGISTRY.histogram(
    "geomesa_io_read_seconds", "partition file read time (per file)"
)
io_decode_seconds = REGISTRY.histogram(
    "geomesa_io_decode_seconds",
    "Arrow-to-FeatureBatch decode time (per file)",
)
io_stage_seconds = REGISTRY.histogram(
    "geomesa_io_stage_seconds",
    "host column staging time (per slab chunk)",
)
io_prefetch_depth = REGISTRY.gauge(
    "geomesa_io_prefetch_depth", "prefetch chunks in flight"
)
io_queue_bytes = REGISTRY.gauge(
    "geomesa_io_queue_bytes",
    "decoded chunk bytes waiting in the prefetch queue",
)
io_chunks = REGISTRY.counter(
    "geomesa_io_chunks_total", "chunks delivered by the prefetch pipeline"
)
io_bytes_read = REGISTRY.counter(
    "geomesa_io_bytes_read_total", "partition file bytes read from disk"
)

# crash-consistent FS store (store/fs.py): generation publishes, what
# the recovery sweep reclaimed from interrupted flushes, checksum
# verification failures (and the partitions they quarantined), and
# transient-read retries spent by the prefetch workers
store_generations = REGISTRY.counter(
    "geomesa_store_generations_published_total",
    "partition-file generations atomically published by flushes",
)
store_orphan_files = REGISTRY.counter(
    "geomesa_store_orphan_files_reclaimed_total",
    "orphaned partition/tmp files reclaimed by the recovery sweep",
)
store_orphan_bytes = REGISTRY.counter(
    "geomesa_store_orphan_bytes_reclaimed_total",
    "bytes reclaimed by the recovery sweep",
)
store_checksum_failures = REGISTRY.counter(
    "geomesa_store_checksum_failures_total",
    "partition files that failed checksum verification",
)
store_quarantined = REGISTRY.gauge(
    "geomesa_store_partitions_quarantined",
    "partitions currently quarantined by checksum failures (best-effort:"
    " summed over store instances; /stats/store has the exact per-type"
    " sets)",
)
store_read_retries = REGISTRY.counter(
    "geomesa_store_read_retries_total",
    "transient partition-read retries by the prefetch workers",
)

# chunked partition format v2 (store/chunkstats.py): how much of the
# streamed-scan workload the chunk-level Z/bbox/time pruning index
# removed BEFORE read/decode (bytes skipped are real file bytes -- the
# pruned parquet row groups), and fsck's chunk-stat drift findings
store_chunks_read = REGISTRY.counter(
    "geomesa_store_chunks_read_total",
    "v2 partition chunks read by chunk-planned scans",
)
store_chunks_skipped = REGISTRY.counter(
    "geomesa_store_chunks_skipped_total",
    "v2 partition chunks pruned before read/decode",
)
store_chunk_bytes_skipped = REGISTRY.counter(
    "geomesa_store_chunk_bytes_skipped_total",
    "encoded partition-file bytes skipped by chunk pruning",
)
store_chunk_stat_drift = REGISTRY.counter(
    "geomesa_store_chunk_stat_drift_total",
    "chunk-stat records that disagreed with decoded rows (fsck)",
)

# aggregation pushdown (store/pushdown.py): density/count/stats queries
# answered from chunk pre-aggregates -- how often it engages (by kind),
# how often an eligible-looking query fell back, and the interior rows
# that were never read vs the boundary chunks that row-refined
agg_pushdown_queries = REGISTRY.counter(
    "geomesa_agg_pushdown_queries_total",
    "aggregate queries answered from chunk pre-aggregates",
)
agg_pushdown_fallbacks = REGISTRY.counter(
    "geomesa_agg_pushdown_fallback_total",
    "aggregate queries that fell back to the row-scan path",
)
agg_pushdown_rows = REGISTRY.counter(
    "geomesa_agg_pushdown_rows_preaggregated_total",
    "rows answered from interior-chunk summaries without being read",
)
agg_pushdown_chunks_refined = REGISTRY.counter(
    "geomesa_agg_pushdown_chunks_refined_total",
    "boundary chunks that descended to row-level refinement",
)

# fault-tolerant serving (resilience.py): breaker state machines per
# failure domain (0 closed / 1 half-open / 2 open; the keyed partition
# domain exposes open counts via /readyz instead), serving-path
# retries, degraded answers by reason, watchdog interventions, OOM
# batch-halving recoveries and scheduler-worker crash-replacements
resilience_breaker_state = REGISTRY.gauge(
    "geomesa_resilience_breaker_state",
    "circuit-breaker state per domain (0=closed 1=half-open 2=open)",
)
resilience_breaker_transitions = REGISTRY.counter(
    "geomesa_resilience_breaker_transitions_total",
    "circuit-breaker state transitions (domain, to)",
)
resilience_retries = REGISTRY.counter(
    "geomesa_resilience_retries_total",
    "serving-path retries of retryable faults (by domain)",
)
resilience_degraded = REGISTRY.counter(
    "geomesa_resilience_degraded_total",
    "requests answered degraded, by (bounded) reason",
)
resilience_watchdog_timeouts = REGISTRY.counter(
    "geomesa_resilience_watchdog_timeouts_total",
    "stuck device launches failed by the scheduler watchdog",
)
resilience_oom_recoveries = REGISTRY.counter(
    "geomesa_resilience_oom_recoveries_total",
    "staging/HBM OOMs recovered by halving the scan batch",
)
sched_worker_failures = REGISTRY.counter(
    "geomesa_sched_worker_failures_total",
    "scheduler worker crashes survived (requests failed typed, worker "
    "kept serving)",
)
sched_drains = REGISTRY.counter(
    "geomesa_sched_drains_total",
    "graceful drains completed (admission stopped, in-flight finished)",
)

# multi-chip sharded serving (parallel/dist.py + device_cache.py): mesh
# topology and residency per shard (bounded labels: shard indexes are
# capped by the device count), mesh-wide scan launches, exchange
# capacity retries (an adversarial layout relaunched at the measured
# block bound) and mesh builds that fell back to the host sort
mesh_shards = REGISTRY.gauge(
    "geomesa_mesh_shards",
    "shards in the serving mesh (0 = single-device serving)",
)
mesh_resident_rows = REGISTRY.gauge(
    "geomesa_mesh_resident_rows",
    "resident rows per mesh shard (shard label; padding excluded)",
)
mesh_resident_bytes = REGISTRY.gauge(
    "geomesa_mesh_resident_bytes",
    "resident device bytes per mesh shard (shard label)",
)
mesh_launches = REGISTRY.counter(
    "geomesa_mesh_launches_total",
    "mesh-wide sharded scan launches (fused groups count once)",
)
mesh_build_seconds = REGISTRY.histogram(
    "geomesa_mesh_build_seconds",
    "mesh-resident index build time (distributed sort + shard staging)",
)
mesh_exchange_retries = REGISTRY.counter(
    "geomesa_mesh_exchange_retries_total",
    "distributed-sort exchanges relaunched at the measured capacity",
)
mesh_build_fallbacks = REGISTRY.counter(
    "geomesa_mesh_build_fallbacks_total",
    "mesh index builds that degraded to the host sort",
)

# persistent serving compile cache (jaxconf.py): task-level hit/miss as
# observed through jax's compilation-cache monitoring events, split by
# tier — tier="disk" counts executables loaded from the persistent
# on-disk cache instead of compiled, tier="inproc" counts dispatches
# that reused an executable already built in this process's jit caches
# (device_cache._note_jit_cache)
compile_cache_hits = REGISTRY.counter(
    "geomesa_compile_cache_hits_total",
    "XLA executable reuse by tier (disk = persistent cache load, "
    "inproc = in-process jit-cache hit)",
)
compile_cache_requests = REGISTRY.counter(
    "geomesa_compile_cache_requests_total",
    "XLA compilations eligible for the persistent cache (misses = "
    "requests - hits)",
)

# AOT warmup (warmup.py): progress of the start-time pre-compile pass
# over the bucket x kernel-family signature set, by state label —
# state="total" planned signatures, state="compiled" legs that paid a
# backend compile, state="from_cache" legs satisfied entirely from the
# persistent/in-process caches, state="failed" legs that raised
warmup_signatures = REGISTRY.gauge(
    "geomesa_warmup_signatures",
    "AOT warmup signatures by state (total/compiled/from_cache/failed)",
)

# per-request tracing (tracing.py): how many traces the ring retained
# (head-sampled or slow-captured) and how many crossed the slow-query
# threshold (trace.slow_ms) — the rate the slow-query log grows at
traces_captured = REGISTRY.counter(
    "geomesa_traces_captured_total",
    "request traces retained in the recent-trace ring",
)
slow_queries = REGISTRY.counter(
    "geomesa_slow_queries_total",
    "requests slower than trace.slow_ms (always-captured + slow-logged)",
)

# serving SLO engine (slo.py): latency observations per endpoint/lane
# (bucket exemplars carry trace ids — a p99 violation on /metrics
# resolves to a captured trace), good/bad per SLO name, burn-rate
# gauges per (slo, fast|slow) window, flight-recorder bundles by
# (bounded) reason
slo_latency = REGISTRY.histogram(
    "geomesa_slo_latency_seconds",
    "request latency per endpoint/lane (buckets carry trace exemplars)",
    buckets=(
        0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
        0.75, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
    ),
)
slo_requests = REGISTRY.counter(
    "geomesa_slo_requests_total", "requests measured against an SLO"
)
slo_bad = REGISTRY.counter(
    "geomesa_slo_bad_total",
    "requests over their SLO latency threshold or failed 5xx",
)
slo_burn = REGISTRY.gauge(
    "geomesa_slo_burn_rate",
    "error-budget burn rate per (slo, window); > 1 consumes budget "
    "faster than it accrues",
)
flightrec_bundles = REGISTRY.counter(
    "geomesa_flightrec_bundles_total",
    "flight-recorder postmortem bundles written, by reason",
)

# per-request cost ledger (ledger.py): requests folded into the
# process aggregates, attributed device/compile seconds, and the raw
# compile events the compile ledger observed through jax.monitoring
ledger_requests = REGISTRY.counter(
    "geomesa_ledger_requests_total",
    "requests folded into the cost ledger",
)
ledger_device_seconds = REGISTRY.counter(
    "geomesa_ledger_device_seconds_total",
    "fair-share device seconds attributed to ledgered requests",
)
ledger_compile_seconds = REGISTRY.counter(
    "geomesa_ledger_compile_seconds_total",
    "XLA compile seconds ledgered requests blocked on",
)
compile_events = REGISTRY.counter(
    "geomesa_compile_events_total",
    "XLA backend compiles observed by the compile ledger",
)
compile_event_seconds = REGISTRY.counter(
    "geomesa_compile_event_seconds_total",
    "total XLA backend compile seconds observed by the compile ledger",
)

# streaming live layer (store/stream.py + store/wal.py): WAL-backed
# incremental ingest, the in-memory generation it serves from, and the
# backpressured background compaction into the partition files
stream_appends = REGISTRY.counter(
    "geomesa_stream_appends_total", "acked streaming append calls"
)
stream_rows = REGISTRY.counter(
    "geomesa_stream_rows_total", "rows acked through the streaming layer"
)
stream_wal_bytes = REGISTRY.counter(
    "geomesa_stream_wal_bytes_total", "bytes appended to WAL segments"
)
stream_wal_fsyncs = REGISTRY.counter(
    "geomesa_stream_wal_fsyncs_total", "WAL fsync calls (durability acks)"
)
stream_wal_replay_rows = REGISTRY.counter(
    "geomesa_stream_wal_replay_rows_total",
    "rows recovered into the memtable by WAL replay at open",
)
stream_wal_truncations = REGISTRY.counter(
    "geomesa_stream_wal_truncations_total",
    "torn WAL tails truncated at the last valid checksum during replay",
)
stream_memtable_rows = REGISTRY.gauge(
    "geomesa_stream_memtable_rows",
    "rows live in the in-memory generation (not yet compacted)",
)
stream_memtable_runs = REGISTRY.gauge(
    "geomesa_stream_memtable_runs",
    "Z-sorted memtable runs live (the per-query read amplification)",
)
stream_backpressure = REGISTRY.counter(
    "geomesa_stream_backpressure_total",
    "appends rejected 429-style at the wal.max.generations bound",
)
stream_compactions = REGISTRY.counter(
    "geomesa_stream_compactions_total",
    "memtable generations compacted into partition files",
)
stream_compact_seconds = REGISTRY.histogram(
    "geomesa_stream_compact_seconds",
    "background compaction duration (merge + flush + WAL truncate)",
)
stream_compact_yields = REGISTRY.counter(
    "geomesa_stream_compact_yields_total",
    "compactor pauses yielded to serving load (brownout signal)",
)
stream_delta_refreshes = REGISTRY.counter(
    "geomesa_stream_delta_refreshes_total",
    "resident-index refreshes from streamed appends, by mode "
    "(delta = incremental into the validity-planed buffers, "
    "restage = fallback full restage)",
)

# runtime lock-order checker (analysis/lockcheck.py): the acquisition
# graph's size and its findings -- nonzero cycles or blocking events in
# a checked process is a concurrency regression (gauges, set whenever
# LockCheck.report() runs; zero and flat is the healthy shape)
lockcheck_locks = REGISTRY.gauge(
    "geomesa_lockcheck_locks", "checked locks registered this process"
)
lockcheck_edges = REGISTRY.gauge(
    "geomesa_lockcheck_edges", "distinct lock acquisition-order edges"
)
lockcheck_cycles = REGISTRY.gauge(
    "geomesa_lockcheck_cycles",
    "lock-order cycles detected (ABBA deadlock potentials)",
)
lockcheck_blocking = REGISTRY.gauge(
    "geomesa_lockcheck_blocking_events",
    "blocking calls observed under a held (non-blocking_ok) lock",
)

# runtime context-propagation checker (analysis/ctxcheck.py) and
# serving-path recompile tripwire (analysis/compilecheck.py): same
# contract as the lockcheck gauges -- set on report(), zero findings is
# the healthy shape
ctxcheck_tasks = REGISTRY.gauge(
    "geomesa_ctxcheck_tasks", "blessed worker tasks observed this process"
)
ctxcheck_findings = REGISTRY.gauge(
    "geomesa_ctxcheck_findings",
    "context-propagation findings (leaks, mismatched/orphaned accounting)",
)
compilecheck_compiles = REGISTRY.gauge(
    "geomesa_compilecheck_serving_compiles",
    "backend compiles observed while serving was live",
)
compilecheck_violations = REGISTRY.gauge(
    "geomesa_compilecheck_violations",
    "serving-path compiles outside the allowed compile_scope namespace",
)

# device-side spatial join engine (join/): planner strategy choices
# (bounded label: the strategy enum), candidate/pair volumes, batched
# refinement launches, the skew-splitting escape, and the legacy
# window-pairs coarse pass's compaction-cap overflow relaunches
join_queries = REGISTRY.counter(
    "geomesa_join_queries_total",
    "spatial joins executed, by planner strategy",
)
join_candidates = REGISTRY.counter(
    "geomesa_join_candidates_total",
    "candidate (row, window) pairs expanded by join refinement",
)
join_pairs = REGISTRY.counter(
    "geomesa_join_pairs_total", "pairs emitted by the join engine"
)
join_launches = REGISTRY.counter(
    "geomesa_join_launches_total",
    "batched join refinement launches (count + compact each count one)",
)
join_skew_splits = REGISTRY.counter(
    "geomesa_join_skew_splits_total",
    "candidate runs split by the skew escape (hot-cell bound)",
)
join_pair_overflows = REGISTRY.counter(
    "geomesa_join_pair_overflows_total",
    "window-pairs groups whose compaction cap overflowed into a full "
    "bit-plane refetch",
)
join_plan_seconds = REGISTRY.histogram(
    "geomesa_join_plan_seconds", "join planning time (per join)"
)
join_refine_seconds = REGISTRY.histogram(
    "geomesa_join_refine_seconds",
    "join refinement time (expansion + launches + emission, per join)",
)

# Arrow-native result plane (results/): wire-format serving and export
# throughput by (bounded) format label, encode time split from the
# socket write, and the fused device BIN rider's launch count
results_batches = REGISTRY.counter(
    "geomesa_results_batches_total",
    "wire record batches / chunks emitted by the result plane (fmt)",
)
results_bytes = REGISTRY.counter(
    "geomesa_results_bytes_total",
    "response/export body bytes encoded by the result plane (fmt)",
)
results_encode_seconds = REGISTRY.histogram(
    "geomesa_results_encode_seconds",
    "wire-format serialization time per response (socket write excluded)",
)
results_write_seconds = REGISTRY.histogram(
    "geomesa_results_write_seconds",
    "socket write time per response (serialization excluded)",
)
results_bin_device_launches = REGISTRY.counter(
    "geomesa_results_bin_device_launches_total",
    "fused device BIN pack launches (count->cap->compact pairs count one)",
)

# replicated serving tier (replica.py + router.py): WAL shipping volume,
# follower apply/lag, failover accounting and the router front tier's
# per-backend routing outcomes
replica_ship_bytes = REGISTRY.counter(
    "geomesa_replica_ship_bytes_total",
    "WAL record bytes a leader shipped to followers over /wal/<type>",
)
replica_ship_records = REGISTRY.counter(
    "geomesa_replica_ship_records_total",
    "WAL records a leader shipped to followers",
)
replica_apply_records = REGISTRY.counter(
    "geomesa_replica_apply_records_total",
    "shipped WAL records a follower applied into its live layer",
)
replica_apply_skipped = REGISTRY.counter(
    "geomesa_replica_apply_skipped_total",
    "shipped records skipped as already durable here (idempotent replay)",
)
replica_lag_records = REGISTRY.gauge(
    "geomesa_replica_lag_records",
    "records the leader holds that this follower has not applied yet "
    "(summed across types)",
)
replica_failovers = REGISTRY.counter(
    "geomesa_replica_failovers_total",
    "promotions this process performed after a leader-lease expiry",
)
replica_failover_seconds = REGISTRY.histogram(
    "geomesa_replica_failover_seconds",
    "lease-expiry-to-leader-role promotion time per failover",
)
replica_role = REGISTRY.gauge(
    "geomesa_replica_role",
    "replication role of this process (0=follower, 1=promoting, 2=leader)",
)
replica_demotions = REGISTRY.counter(
    "geomesa_replica_demotions_total",
    "leader roles this process surrendered after observing a higher "
    "election epoch (fencing: a stale leader must not take appends)",
)
replica_reprovisions = REGISTRY.counter(
    "geomesa_replica_reprovisions_total",
    "snapshot reprovisions this follower completed (410-gone, gap, "
    "diverged tail or repeated apply failure turned into a rebuild)",
)
replica_reprovision_seconds = REGISTRY.histogram(
    "geomesa_replica_reprovision_seconds",
    "trigger-to-tailing-again time per snapshot reprovision",
)

# snapshot plane (store/snapshot.py + the /snapshot/<type> ship
# endpoint): capture/pin accounting and shipped/installed volume
snapshot_captures = REGISTRY.counter(
    "geomesa_snapshot_captures_total",
    "consistent snapshots captured (pin written under the publish lock)",
)
snapshot_ship_bytes = REGISTRY.counter(
    "geomesa_snapshot_ship_bytes_total",
    "bytes shipped over GET /snapshot/<type> streams",
)
snapshot_ship_files = REGISTRY.counter(
    "geomesa_snapshot_ship_files_total",
    "file records shipped over GET /snapshot/<type> streams",
)
snapshot_installs = REGISTRY.counter(
    "geomesa_snapshot_installs_total",
    "downloaded snapshots swapped into a live tree (write-new-then-"
    "publish install)",
)
snapshot_install_bytes = REGISTRY.counter(
    "geomesa_snapshot_install_bytes_total",
    "bytes of verified snapshot files installed into a live tree",
)
snapshot_pins_reclaimed = REGISTRY.counter(
    "geomesa_snapshot_pins_reclaimed_total",
    "orphaned snapshot pins aged out past snapshot.pin.ttl.s by the "
    "GC/recovery sweep",
)
router_requests = REGISTRY.counter(
    "geomesa_router_requests_total",
    "requests the router front tier completed",
)
router_retries = REGISTRY.counter(
    "geomesa_router_retries_total",
    "reads re-tried on another replica after a backend failure",
)
router_sheds = REGISTRY.counter(
    "geomesa_router_sheds_total",
    "appends shed 503+Retry-After because no leader is known (promotion)",
)
router_backend_errors = REGISTRY.counter(
    "geomesa_router_backend_errors_total",
    "backend attempts that failed (connection error or 5xx)",
)

# continuous-query push tier (pubsub/): registry size, fused match
# launches/latency on the ingest path, delivery/replay volume and the
# teardown/heartbeat accounting on long-lived push streams
pubsub_subscriptions = REGISTRY.gauge(
    "geomesa_pubsub_subscriptions",
    "standing subscriptions currently armed in the registry",
)
pubsub_match_batches = REGISTRY.counter(
    "geomesa_pubsub_match_batches_total",
    "acked append batches matched against the subscription layout "
    "(one fused join launch each, regardless of subscription count)",
)
pubsub_match_pairs = REGISTRY.counter(
    "geomesa_pubsub_match_pairs_total",
    "subscription×feature pairs that survived exact residual + "
    "visibility refinement",
)
pubsub_match_seconds = REGISTRY.histogram(
    "geomesa_pubsub_match_seconds",
    "fused batch×subscriptions match time per acked append batch",
)
pubsub_events_delivered = REGISTRY.counter(
    "geomesa_pubsub_events_delivered_total",
    "alert events written to connected push streams",
)
pubsub_deliver_bytes = REGISTRY.counter(
    "geomesa_pubsub_deliver_bytes_total",
    "push-stream body bytes written to subscribers",
)
pubsub_replay_records = REGISTRY.counter(
    "geomesa_pubsub_replay_records_total",
    "WAL records re-matched below a resuming subscriber's cursor",
)
pubsub_heartbeats = REGISTRY.counter(
    "geomesa_pubsub_heartbeats_total",
    "SSE :keepalive comments written to idle push streams",
)
pubsub_stream_overflows = REGISTRY.counter(
    "geomesa_pubsub_stream_overflows_total",
    "push streams torn down because their live event queue overflowed "
    "(the client resumes exactly-once from its cursor)",
)
pubsub_rearms = REGISTRY.counter(
    "geomesa_pubsub_rearms_total",
    "matcher re-arms from the replicated registry (promotion/recovery)",
)
