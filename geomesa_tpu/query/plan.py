"""Query model + planner.

(ref: geomesa-index-api .../index/planning/QueryPlanner.planQuery,
FilterSplitter, StrategyDecider [UNVERIFIED - empty reference mount]).

Planning steps: parse/normalize the filter; extract spatial + temporal +
attribute bounds; score each available index (heuristic cost, ref
StrategyDecider's stat-less fallback); generate key ranges for the winner;
split device-vs-residual predicates (the FilterTransformIterator analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from geomesa_tpu.features.sft import SimpleFeatureType
from geomesa_tpu.filter import ast
from geomesa_tpu.filter.compile import CompiledFilter, compile_filter
from geomesa_tpu.filter.ecql import parse_ecql
from geomesa_tpu.filter.extract import (
    FilterBounds,
    extract_geometries,
    extract_intervals,
)
from geomesa_tpu.index.api import BuiltIndex, KeyRange
from geomesa_tpu.index.keyspaces import AttributeKeySpace, IdKeySpace


@dataclass
class Query:
    """A GeoTools-Query analog: filter + projection + limits + hints."""

    filter: "ast.Filter | str" = ast.Include
    properties: "list[str] | None" = None  # projection (transform)
    max_features: "int | None" = None
    sort_by: "str | None" = None
    sort_desc: bool = False
    hints: dict = field(default_factory=dict)  # density/stats/bin/sampling

    def parsed(self) -> ast.Filter:
        if isinstance(self.filter, str):
            return parse_ecql(self.filter)
        return self.filter


@dataclass
class QueryPlan:
    """The chosen strategy + ranges + filter split (explain() payload)."""

    sft: SimpleFeatureType
    query: Query
    filter: ast.Filter
    index_name: str
    ranges: "list[KeyRange] | None"
    compiled: CompiledFilter
    geom_bounds: FilterBounds
    time_bounds: FilterBounds
    candidates: "list[tuple[str, float]]" = field(default_factory=list)
    #: aggregation-pushdown routing hint (:func:`aggregate_bounds`):
    #: ``(envelopes, intervals)`` when the filter is EXACTLY a bbox+time
    #: conjunction, so chunk-tolerant density/count/stats queries may be
    #: answered from the v2 manifest's chunk pre-aggregates (interior
    #: chunks from summaries, boundary chunks row-refined). None = the
    #: filter has structure the chunk stats cannot decide -- row scan.
    agg_bounds: "tuple | None" = None

    def explain(self) -> str:
        """Human-readable plan dump (ref: Explainer output surfaced by the
        CLI 'explain' command)."""
        lines = [
            f"Planning query on '{self.sft.type_name}'",
            f"  Filter: {self.filter!r}",
            f"  Strategy candidates: "
            + ", ".join(f"{n} (cost {c:g})" for n, c in self.candidates),
            f"  Chosen index: {self.index_name}",
        ]
        if self.ranges is None:
            lines.append("  Ranges: FULL SCAN (no extractable bounds)")
        else:
            lines.append(f"  Ranges: {len(self.ranges)}")
            for r in self.ranges[:5]:
                lines.append(f"    {r.lo} .. {r.hi}{' (contained)' if r.contained else ''}")
            if len(self.ranges) > 5:
                lines.append(f"    ... {len(self.ranges) - 5} more")
        lines.append(f"  Device predicate: {self.compiled.device_part!r}")
        lines.append(f"  Host residual:    {self.compiled.residual_part!r}")
        return "\n".join(lines)


def plan_query(
    sft: SimpleFeatureType,
    indices: dict,
    query: Query,
    max_ranges: "int | None" = None,
    data_interval: "tuple[int, int] | None" = None,
    stats: "object | None" = None,
) -> QueryPlan:
    """indices: {name: BuiltIndex | IndexKeySpace} -- planning only needs
    the key spaces, so disk-backed stores can plan before loading data.

    The interceptor chain (geomesa_tpu.query.interceptor) rewrites the
    query before planning and can veto the finished plan; ``max_ranges``
    defaults to the three-tier config resolution (SFT user-data
    ``geomesa.scan.ranges.target``, then the system property)."""
    from geomesa_tpu.tracing import span as trace_span

    with trace_span("query.plan", type=sft.type_name) as _tsp:
        return _plan_query(
            sft, indices, query, max_ranges, data_interval, stats, _tsp
        )


def _plan_query(
    sft, indices, query, max_ranges, data_interval, stats, _tsp
) -> QueryPlan:
    from geomesa_tpu.conf import sys_prop
    from geomesa_tpu.query.interceptor import (
        apply_interceptors,
        guard_plan,
        interceptors_for,
    )

    from geomesa_tpu.profiling import profile

    chain = interceptors_for(sft)
    query = apply_interceptors(chain, query, sft)
    if max_ranges is None:
        ud = sft.user_data or {}
        max_ranges = int(
            ud.get("geomesa.scan.ranges.target") or sys_prop("scan.ranges.target")
        )
    f = query.parsed()
    geom_field = sft.geom_field
    dtg_field = sft.dtg_field
    geoms = (
        extract_geometries(f, geom_field) if geom_field else FilterBounds.all()
    )
    intervals = (
        extract_intervals(f, dtg_field) if dtg_field else FilterBounds.all()
    )

    # score every index (ref StrategyDecider: stat-based when stats exist,
    # heuristic otherwise)
    est = _StatEstimator.build(stats) if stats is not None else None
    candidates: list[tuple[str, float]] = []
    for name, built in indices.items():
        ks = getattr(built, "keyspace", built)
        if isinstance(ks, AttributeKeySpace):
            bounds = extract_intervals(f, ks.attr)
            eq = _attr_equality(f, ks.attr)
            if est is not None:
                cost = est.attr_cost(ks.attr, eq, bounds)
            else:
                cost = (
                    0.5 if eq else (5.0 if not bounds.unbounded else float("inf"))
                )
            candidates.append((name, cost))
        elif isinstance(ks, IdKeySpace):
            candidates.append((name, float("inf")))
        else:
            heuristic = ks.cost(geoms, intervals)
            if est is not None and heuristic != float("inf"):
                cost = est.spatial_cost(ks, geoms, intervals)
                if cost is None:
                    cost = heuristic
            else:
                cost = heuristic
            candidates.append((name, cost))
    # full scan fallback uses whichever index exists
    candidates.sort(key=lambda t: t[1])
    index_name = candidates[0][0] if candidates else None
    if index_name is None:
        raise ValueError("no indices available")
    if candidates[0][1] == float("inf"):
        # nothing prunes: full scan on the first index
        ranges = None
    else:
        built = indices[index_name]
        ks = getattr(built, "keyspace", built)
        if isinstance(ks, AttributeKeySpace):
            bounds = extract_intervals(f, ks.attr)
            eq = _attr_equality(f, ks.attr)
            if eq is not None:
                ranges = [KeyRange((v,), (v,), False) for v in eq]
            else:
                ranges = ks.ranges_for_values(bounds)
        else:
            with profile("plan.scan_ranges"):
                ranges = ks.scan_ranges(
                    geoms, intervals, max_ranges, data_interval=data_interval
                )
    compiled = compile_filter(f, sft)
    plan = QueryPlan(
        sft=sft,
        query=query,
        filter=f,
        index_name=index_name,
        ranges=ranges,
        compiled=compiled,
        geom_bounds=geoms,
        time_bounds=intervals,
        candidates=candidates,
        agg_bounds=aggregate_bounds(f, sft, geoms, intervals),
    )
    guard_plan(chain, plan)
    _tsp.set(
        index=index_name,
        ranges=len(ranges) if ranges is not None else "full-scan",
    )
    return plan


def is_aggregate_shape(f, sft) -> bool:
    """Structural half of :func:`aggregate_bounds` -- True when ``f`` is
    a conjunction of envelope predicates on the default geometry and
    closed intervals on the default dtg (or INCLUDE). Cheap (no bound
    extraction, no planning): pushdown entry points pre-screen with this
    before paying for a full query plan they would then discard."""
    geom_field = sft.geom_field
    dtg_field = sft.dtg_field

    def _pure(node) -> bool:
        if node is ast.Include:
            return True
        if isinstance(node, ast.BBox) and node.attr == geom_field:
            return True
        if isinstance(node, ast.During) and node.attr == dtg_field:
            return True
        if (
            isinstance(node, ast.Between)
            and node.attr == dtg_field
            and isinstance(node.lo, (int, float))
            and isinstance(node.hi, (int, float))
        ):
            return True
        return False

    nodes = f.children if isinstance(f, ast.And) else (f,)
    return all(_pure(n) for n in nodes)


def aggregate_bounds(f, sft, geoms, intervals) -> "tuple | None":
    """The planner's aggregation-pushdown routing test: ``(envs, ivals)``
    when ``f`` is EXACTLY a conjunction of envelope predicates on the
    default geometry and closed intervals on the default dtg (or
    INCLUDE) -- the shapes chunk statistics can decide. ``envs``/
    ``ivals`` follow the classify() convention: None = unconstrained on
    that dimension, an empty tuple = provably empty. Any other filter
    structure (attribute predicates, NOT, OR, exact geometries, open
    comparisons) returns None and aggregates take the row-scan path.

    Soundness: an INTERIOR chunk (bbox inside one envelope, time range
    inside one interval) then contains ONLY rows satisfying ``f`` --
    a feature's envelope lies within its chunk's bbox, so containment
    implies the bbox predicate for point and extent geometries alike."""
    if not is_aggregate_shape(f, sft):
        return None
    envs = (
        None
        if geoms.unbounded
        else tuple(env for env, _ in geoms.values)
    )
    ivals = None if intervals.unbounded else tuple(intervals.values)
    return (envs, ivals)


class _StatEstimator:
    """Stat-based candidate costing (ref StrategyDecider + GeoMesaStats):
    costs are estimated rows scanned, derived from the write-time stats
    (CountStat total, per-attribute MinMax, Z3Histogram occupancy)."""

    def __init__(self, total, minmax, z3hist, cardinality):
        self.total = total
        self.minmax = minmax  # attr -> MinMax
        self.z3hist = z3hist
        self.cardinality = cardinality  # attr -> Cardinality (HLL)

    @staticmethod
    def build(stats) -> "_StatEstimator | None":
        from geomesa_tpu.stats.sketches import (
            Cardinality,
            CountStat,
            MinMax,
            Z3HistogramStat,
        )

        total = None
        minmax: dict = {}
        z3hist = None
        cardinality: dict = {}
        for s in getattr(stats, "stats", []):
            if isinstance(s, CountStat):
                total = s.count
            elif isinstance(s, MinMax):
                minmax[s.attr] = s
            elif isinstance(s, Z3HistogramStat):
                z3hist = s
            elif isinstance(s, Cardinality):
                cardinality[s.attr] = s
        if total is None:
            return None
        return _StatEstimator(total, minmax, z3hist, cardinality)

    def attr_cost(self, attr, eq, bounds) -> float:
        if eq is not None:
            card = self.cardinality.get(attr)
            distinct = card.estimate if card is not None else 0.0
            if distinct >= 1.0:
                # rows per distinct value x values requested (HLL-backed)
                per_value = self.total / distinct
            else:
                per_value = self.total * 0.001  # high-cardinality guess
            return max(1.0, min(self.total, per_value * len(eq)))
        if bounds.unbounded:
            return float("inf")
        mm = self.minmax.get(attr)
        if mm is None:
            return self.total * 0.5
        frac = 0.0
        for lo, hi in bounds.values:
            frac += mm.selectivity(lo, hi)
        return self.total * min(1.0, frac)

    def _time_fraction(self, ks, intervals) -> float:
        mm = self.minmax.get(getattr(ks, "dtg_field", None))
        if mm is None:
            return 1.0
        return min(
            1.0, sum(mm.selectivity(lo, hi) for lo, hi in intervals.values)
        )

    def spatial_cost(self, ks, geoms, intervals) -> "float | None":
        """Estimated rows for z3/xz3 (occupancy histogram) and z2/xz2
        (time-marginalized histogram, area-fraction fallback). Always in
        rows so candidates stay comparable with attribute estimates; all
        spatial candidates share the same data-aware model so clustered
        data cannot bias the choice. None only when no estimate is
        possible at all."""
        # structural: temporal keyspaces (z3/xz3) carry a dtg_field
        needs_time = getattr(ks, "dtg_field", None) is not None
        if geoms.empty or (needs_time and intervals.empty):
            return 1.0
        if needs_time and intervals.unbounded:
            return None  # keyspace cost is inf anyway
        if geoms.unbounded:
            # no spatial prune: rows bounded only by the time fraction
            tfrac = self._time_fraction(ks, intervals) if needs_time else 1.0
            return max(1.0, self.total * tfrac)
        if self.z3hist is not None:
            if needs_time:
                est = self.z3hist.estimate(geoms.values, intervals.values)
            else:
                est = self.z3hist.estimate_spatial(geoms.values)
            return max(1.0, est)
        # area-fraction fallback (no histogram: non-point or no-time schema)
        area = 0.0
        for env, _ in geoms.values:
            w = max(0.0, min(env.xmax, 180.0) - max(env.xmin, -180.0))
            h = max(0.0, min(env.ymax, 90.0) - max(env.ymin, -90.0))
            area += w * h
        frac = min(1.0, area / (360.0 * 180.0))
        if needs_time:
            frac *= self._time_fraction(ks, intervals)
        return max(1.0, self.total * frac)


def as_query(q) -> Query:
    """Coerce a Query | ECQL string | ast.Filter to a Query (shared by all
    store implementations)."""
    if isinstance(q, Query):
        return q
    return Query(filter=q)


def internal_query(f, auths=None) -> Query:
    """A maintenance/candidate-scan query: exempt from user-facing caps
    like the global ``query.max.features`` (truncating an age-off sweep or
    a kNN candidate scan would corrupt the result). ``auths`` carries the
    caller's row-security context — omitted means none (fail closed)."""
    hints = {"internal": True}
    if auths is not None:
        hints["auths"] = auths
    return Query(filter=f, hints=hints)


def _attr_equality(f: ast.Filter, attr: str):
    """Equality/IN value set for an attribute if the filter pins it
    (top-level or within an AND), else None."""
    nodes = f.children if isinstance(f, ast.And) else (f,)
    for n in nodes:
        if isinstance(n, ast.Compare) and n.op == "=" and n.attr == attr:
            return (n.value,)
        if isinstance(n, ast.In) and n.attr == attr:
            return tuple(sorted(n.values))
    return None
