"""Query planning & execution (maps reference L5 planning + scan execution).

- ``plan``:   Query/QueryPlan model, StrategyDecider, range generation
              (ref: geomesa-index-api .../index/planning/QueryPlanner.scala,
              FilterSplitter.scala, StrategyDecider.scala)
- ``runner``: partition-pruned device scan + residual + local post-processing
              (ref: LocalQueryRunner + the server-side iterator stack, which
              here runs as fused device masks)
"""

from geomesa_tpu.query.plan import Query, QueryPlan, plan_query
from geomesa_tpu.query.runner import run_query

__all__ = ["Query", "QueryPlan", "plan_query", "run_query"]
