"""Query execution: partition prune -> device mask scan -> residual ->
local post-processing.

(ref: the scan side of AccumuloQueryPlan.BatchScanPlan + LocalQueryRunner
[UNVERIFIED - empty reference mount]. The reference fans ranges out to
tablet servers; here partitions are scanned with one jitted fused mask --
same shape = one XLA executable -- and non-device predicates run as an
exact numpy residual over surviving candidates only.)
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from geomesa_tpu.features.batch import FeatureBatch
from geomesa_tpu.index.api import BuiltIndex
from geomesa_tpu.ops.scan import stage_columns
from geomesa_tpu.query.plan import QueryPlan


@dataclass
class QueryResult:
    batch: FeatureBatch
    plan: QueryPlan
    scanned: int  # rows device-scanned after pruning
    total: int  # rows in the index

    def __len__(self) -> int:
        return len(self.batch)


MAX_RUN_PARTS = 8


def _contiguous_runs(parts) -> "list[tuple[int, int]]":
    """Merge adjacent surviving partitions into [start, stop) runs: the
    predicate is elementwise, so one staging + one kernel launch per run
    instead of per partition (a BatchScanner coalescing its ranges).
    Runs cap at MAX_RUN_PARTS partitions so the set of kernel shapes --
    and therefore jit recompiles across differently-pruned queries --
    stays small."""
    runs: list = []
    counts: list = []
    for p in parts:
        if runs and runs[-1][1] == p.start and counts[-1] < MAX_RUN_PARTS:
            runs[-1][1] = p.stop
            counts[-1] += 1
        else:
            runs.append([p.start, p.stop])
            counts.append(1)
    return [(a, b) for a, b in runs]


def run_query(built: BuiltIndex, plan: QueryPlan) -> QueryResult:
    from geomesa_tpu.profiling import profile
    from geomesa_tpu.tracing import span

    with profile("query.scan"), span("query.scan") as sp:
        res = _run_query(built, plan)
        sp.set(scanned=res.scanned, hits=len(res))
        return res


def _device_trace_ctx():
    """The ``trace.device.dir`` hook: a SAMPLED request's device launch
    is additionally wrapped in a ``jax.profiler`` dump (kernel timings,
    HBM traffic) when the knob names a directory — the host-side trace
    says WHICH launch was slow, the profiler dump says why."""
    from contextlib import nullcontext

    from geomesa_tpu.conf import sys_prop
    from geomesa_tpu.tracing import current_trace

    log_dir = str(sys_prop("trace.device.dir") or "")
    if not log_dir:
        return nullcontext()
    t = current_trace()
    if t is None or not t.sampled:
        return nullcontext()
    from geomesa_tpu.profiling import device_trace

    return device_trace(log_dir)


#: OOM-recovery recursion bound: halving a run more times than this
#: means the device cannot hold even a sliver — give up loudly
_MAX_OOM_SPLITS = 8


def _scan_run(built, compiled, jitted, start: int, stop: int,
              depth: int = 0) -> np.ndarray:
    """One staged device launch over rows [start, stop) returning the
    fetched mask. Staging/HBM OOM (or the ``fail.stage.oom`` injection)
    recovers by HALVING the run and retrying each half — a transient
    memory squeeze (concurrent staging, fragmentation) costs extra
    launches, not the query; anything else propagates to the fault
    taxonomy upstream."""
    from geomesa_tpu.failpoints import FailpointError, fail_point
    from geomesa_tpu.tracing import span

    try:
        import time as _time

        from geomesa_tpu import ledger

        t_stage = _time.perf_counter()
        with span(
            "device.launch", rows=int(stop - start)
        ), _device_trace_ctx(), \
                ledger.compile_scope("store.scan"):
            fail_point("fail.device.launch")
            fail_point("fail.stage.oom")
            cols = stage_columns(
                built.batch, compiled.device_cols, start, stop
            )
            t_launch = _time.perf_counter()
            out = np.asarray(jitted(cols))  # lint: disable=GT004(the mask fetch IS the launch's intended sync point -- one per contiguous run, not per row)
        # store-path launches never pass through the scheduler's device
        # accounting: charge the requesting ledger here instead — the
        # host column staging charges as STAGE time, only the jitted
        # dispatch+fetch as device time (the cross-tenant device-time
        # sums must mean what they say)
        done = _time.perf_counter()
        ledger.charge("stage_seconds", t_launch - t_stage)
        ledger.charge("device_launches", 1)
        ledger.charge("device_seconds", done - t_launch)
        return out
    except Exception as e:
        from geomesa_tpu import resilience

        # fail.stage.oom's FailpointError SIMULATES an OOM at this site;
        # a real one surfaces as RESOURCE_EXHAUSTED / MemoryError. Match
        # on WHICH failpoint fired — fail.device.launch raises the same
        # type here and must take the launch-failure path, not halving
        oom = resilience.is_oom(e) or (
            isinstance(e, FailpointError)
            and getattr(e, "name", None) == "fail.stage.oom"
        )
        if oom and resilience.enabled() and stop - start > 1 \
                and depth < _MAX_OOM_SPLITS:
            from geomesa_tpu import metrics

            metrics.resilience_oom_recoveries.inc()
            mid = (start + stop) // 2
            return np.concatenate([
                _scan_run(built, compiled, jitted, start, mid, depth + 1),
                _scan_run(built, compiled, jitted, mid, stop, depth + 1),
            ])
        if (
            resilience.degrade_allowed()
            and resilience.classify(e) != resilience.FATAL
        ):
            # device rung unavailable (launch failed / stuck / OOM too
            # small to split): evaluate the SAME predicate on the host
            # rows — exact, just slower — so the store scan path keeps
            # answering with a dead accelerator. The residual re-applies
            # downstream; it is a subset of the full host predicate, so
            # the double application is idempotent.
            resilience.note_degraded(
                "device-oom" if oom else "device-launch-failed"
            )
            rows = built.batch.take(np.arange(start, stop))
            return np.asarray(compiled.host_mask(rows), dtype=bool)
        raise


def _run_query(built: BuiltIndex, plan: QueryPlan) -> QueryResult:
    import jax

    parts = built.prune(plan.ranges)
    compiled = plan.compiled
    n_scanned = sum(p.count for p in parts)

    hit_chunks: list[np.ndarray] = []
    if parts:
        use_device = bool(compiled.device_cols)
        jitted = None
        if use_device:
            _, jitted = compiled.jitted_scan()
        for start, stop in _contiguous_runs(parts):
            if use_device:
                # one span per kernel launch: stage + dispatch + the
                # mask fetch (np.asarray is the sync point)
                mask = _scan_run(built, compiled, jitted, start, stop)
            else:
                mask = np.ones(stop - start, dtype=bool)
            idx = np.nonzero(mask)[0]
            if len(idx) and not compiled.fully_on_device:
                cand = built.batch.take(idx + start)
                idx = idx[compiled.residual_mask(cand)]
            if len(idx):
                hit_chunks.append(idx + start)

    if hit_chunks:
        rows = np.concatenate(hit_chunks)
    else:
        rows = np.array([], dtype=np.int64)

    # internal per-partition scans (fs store) feed a merge that copies;
    # let a full-match scan skip the identity gather there. User-facing
    # results always copy (a caller mutating its result must never tear
    # the store's partition cache).
    internal = bool(plan.query.hints.get("internal_scan"))
    result = built.batch.take(rows, allow_alias=internal)
    result = _post_process(result, plan)
    return QueryResult(result, plan, n_scanned, built.n)


def _post_process(batch: FeatureBatch, plan: QueryPlan) -> FeatureBatch:
    """visibility / sort / max-features / projection (ref
    LocalQueryRunner + Accumulo cell-visibility filtering)."""
    q = plan.query
    # Accumulo semantics: a labeled feature is hidden unless the query's
    # auths satisfy it -- including when no auths were supplied at all.
    # Internal per-partition scans (fs store) defer this to the outer,
    # global post-process so the real auths are the ones applied.
    # raw_visibility is the resident-cache STAGING escape hatch: the
    # DeviceIndex stages every row plus a label-id plane and enforces
    # visibility itself per request (device auth-table gather); it must
    # never be set on a user-facing query.
    if not q.hints.get("internal_scan") and not q.hints.get("raw_visibility"):
        from geomesa_tpu.security import filter_by_visibility

        m = filter_by_visibility(batch, q.hints.get("auths", ()))
        if m is not None:
            batch = batch.take(np.nonzero(m)[0])
    if q.sort_by:
        order = np.argsort(batch.column(q.sort_by), kind="stable")
        if q.sort_desc:
            order = order[::-1]
        batch = batch.take(order)
    if q.max_features is not None and len(batch) > q.max_features:
        batch = batch.take(np.arange(q.max_features))
    if q.properties:
        from geomesa_tpu.features.sft import SimpleFeatureType

        attrs = tuple(
            batch.sft.descriptor(p) for p in q.properties
        )
        sub_sft = SimpleFeatureType(
            batch.sft.type_name, attrs, batch.sft.user_data
        )
        batch = FeatureBatch(
            sub_sft,
            batch.fids,
            {p: batch.columns[p] for p in q.properties},
        )
    return batch
