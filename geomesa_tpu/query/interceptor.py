"""Query interceptors and planner guard rails.

Ref role: geomesa-index-api .../planning/QueryInterceptor [UNVERIFIED -
empty reference mount]: per-schema hooks that rewrite queries before
planning and/or veto plans after (the reference's guard example is the
full-table-scan block). Interceptors are declared in SFT user data as
dotted class paths::

    geomesa.query.interceptors = "my.module.MyInterceptor:other.Hook"

(``:`` separates multiple interceptors so the declaration survives the
comma-delimited SFT spec string round-trip; ``,`` also works when the
user data is built programmatically). Instances are created once per
declaration and cached, so stateful interceptors keep state across
queries. The built-in ``FullTableScanGuard`` activates via the
``query.block.full.table`` system property or the
``geomesa.block.full.table`` SFT user-data flag.
"""

from __future__ import annotations

import importlib

from geomesa_tpu.conf import sys_prop
from geomesa_tpu.filter import ast

USER_DATA_KEY = "geomesa.query.interceptors"
BLOCK_SCAN_KEY = "geomesa.block.full.table"


class QueryInterceptor:
    """Subclass hooks; either may be a no-op."""

    def rewrite(self, query, sft):
        """Return a (possibly modified) Query before planning."""
        return query

    def guard(self, plan) -> None:
        """Raise to veto a finished plan."""


class FullTableScanGuard(QueryInterceptor):
    """Vetoes plans that would scan every row (ref the reference's
    block-full-table guard)."""

    def guard(self, plan) -> None:
        # internal/maintenance scans (age-off sweeps, process fallbacks)
        # are exempt, same as MaxFeaturesInterceptor
        if plan.ranges is None and not plan.query.hints.get("internal"):
            raise ValueError(
                f"full-table scan of {plan.sft.type_name!r} blocked "
                f"(filter {plan.filter!r} prunes nothing; disable via the "
                f"query.block.full.table property)"
            )


class MaxFeaturesInterceptor(QueryInterceptor):
    """Applies the global ``query.max.features`` cap to unbounded
    user-facing queries. Internal/maintenance queries (age-off sweeps,
    process candidate scans) opt out via the ``internal`` query hint --
    truncating those would silently corrupt their results."""

    def rewrite(self, query, sft):
        cap = sys_prop("query.max.features")
        if cap and query.max_features is None and not query.hints.get("internal"):
            import dataclasses

            return dataclasses.replace(query, max_features=cap)
        return query


def _load_dotted(path: str):
    mod, _, name = path.strip().rpartition(".")
    if not mod:
        raise ValueError(f"bad interceptor path {path!r}")
    return getattr(importlib.import_module(mod), name)


# instances cached per declaration string (NOT in sft.user_data: anything
# placed there is serialized into the spec string and would corrupt
# persisted schema.json manifests)
_DECLARED_CACHE: dict = {}


def _declared_instances(declared: str) -> list:
    cached = _DECLARED_CACHE.get(declared)
    if cached is None:
        cached = []
        for path in declared.replace(",", ":").split(":"):
            if not path.strip():
                continue
            cls = _load_dotted(path)
            cached.append(cls() if isinstance(cls, type) else cls)
        _DECLARED_CACHE[declared] = cached
    return cached


def interceptors_for(sft) -> list:
    """The interceptor chain for a schema: built-ins (re-evaluated each
    call, so property flips take effect) + user-data-declared classes."""
    chain: list = [MaxFeaturesInterceptor()]
    ud = getattr(sft, "user_data", None)
    if ud is None:
        ud = {}
    if sys_prop("query.block.full.table") or _truthy(ud.get(BLOCK_SCAN_KEY)):
        chain.append(FullTableScanGuard())
    declared = ud.get(USER_DATA_KEY)
    if declared:
        chain.extend(_declared_instances(str(declared)))
    return chain


def _truthy(v) -> bool:
    return v is not None and str(v).strip().lower() in (
        "true", "1", "t", "yes", "on",
    )


def apply_interceptors(chain: list, query, sft):
    for ic in chain:
        query = ic.rewrite(query, sft)
    return query


def guard_plan(chain: list, plan) -> None:
    for ic in chain:
        ic.guard(plan)
