"""Query audit log (ref: geomesa-index-api .../audit/ -- AuditWriter,
AuditedEvent, AccumuloAuditWriter writing async to a ``<catalog>_queries``
table [UNVERIFIED - empty reference mount]).

Each executed query emits an AuditedEvent (who, type name, filter string,
planning/scanning millis, hits). Events are appended asynchronously (a
daemon writer thread draining a queue, like the reference's async writer)
as JSON lines to ``<root>/_queries.jsonl`` for filesystem stores, or held
in memory for in-memory stores.
"""

from __future__ import annotations

import atexit
import json
import os
import queue
import threading
import time
from dataclasses import asdict, dataclass, field

from geomesa_tpu.locking import checked_lock
from geomesa_tpu.spawn import spawn_thread


@dataclass
class AuditedEvent:
    store: str
    type_name: str
    filter: str
    user: str = ""
    planning_ms: float = 0.0
    scanning_ms: float = 0.0
    hits: int = 0
    trace_id: str = ""  # cross-links the event to /debug/traces/<id>
    # how the request ended: "ok", "shed" (429), "deadline-expired"
    # (504) or "error" — shed/expired requests audit too (ISSUE 7),
    # not just the ones that executed
    outcome: str = "ok"
    # comma-joined degradation reasons when the answer came from a
    # lower rung (resilience.note_degraded); "" = full-fidelity
    degraded: str = ""
    # event timestamp persisted into the audit log (epoch by design)
    ts: float = field(default_factory=time.time)

    def to_json(self) -> str:
        return json.dumps(asdict(self), sort_keys=True)


class AuditWriter:
    """Async audit sink. Subclasses implement _write(event).

    Lifecycle: the drain thread is a daemon (it must never keep a
    process alive), which means a short-lived CLI process could exit
    with events still queued — :meth:`close` drains and stops the
    thread, and is registered via ``atexit`` when the thread first
    starts so every normal interpreter exit flushes implicitly."""

    _STOP = object()  # drain-thread shutdown sentinel

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._thread = spawn_thread(
            self._drain, name="audit-drain", context=False
        )
        self._started = False
        self._closed = False
        self._lock = checked_lock("audit.writer")

    def write(self, event: AuditedEvent) -> None:
        with self._lock:
            if not self._closed:
                if not self._started:
                    self._thread.start()
                    self._started = True
                    atexit.register(self.close)
                # enqueue UNDER the lock: a put after close() drained the
                # queue would be silently lost (the race close exists to
                # fix)
                self._q.put(event)
                return
        # post-close stragglers write synchronously (losing them silently
        # would defeat close()'s whole purpose) -- OUTSIDE the state lock:
        # _write does file I/O, serialized by its own _flock. _closed
        # never unsets, so the flag read above cannot go stale.
        try:
            self._write(event)
        except Exception:
            pass

    def flush(self, timeout: float = 5.0) -> None:
        if self._started:
            # unfinished_tasks (not empty()) -- the drain thread removes an
            # event from the queue before _write completes. Monotonic: a
            # wall-clock step here would stretch (or cut short) close()'s
            # drain bound.
            deadline = time.monotonic() + timeout
            while self._q.unfinished_tasks and time.monotonic() < deadline:
                time.sleep(0.005)

    def close(self, timeout: float = 5.0) -> None:
        """Drain every queued event and stop the writer thread. Safe to
        call repeatedly; subsequent writes fall back to synchronous."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            started = self._started
        if not started:
            return
        self.flush(timeout)
        self._q.put(self._STOP)
        self._thread.join(timeout=timeout)

    def _drain(self) -> None:
        while True:
            ev = self._q.get()
            try:
                if ev is self._STOP:
                    return
                self._write(ev)
            except Exception:
                pass  # audit must never take down the query path
            finally:
                self._q.task_done()

    def _write(self, event: AuditedEvent) -> None:  # pragma: no cover
        raise NotImplementedError


class MemoryAuditWriter(AuditWriter):
    def __init__(self):
        super().__init__()
        self.events: list = []

    def _write(self, event: AuditedEvent) -> None:
        self.events.append(event)


class FileAuditWriter(AuditWriter):
    """JSONL audit file -- the `<catalog>_queries` table analog."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        # serializes appends to the JSONL file; holding it across the
        # write IS its purpose (one un-torn line per event)
        self._flock = checked_lock("audit.file", blocking_ok=True)

    def _write(self, event: AuditedEvent) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        # lint: disable=GT002(append serialization is this lock's purpose)
        with self._flock, open(self.path, "a") as fh:
            fh.write(event.to_json() + "\n")  # lint: disable=GT002(same: ordered append under the append lock)

    def read_events(self) -> list:
        if not os.path.exists(self.path):
            return []
        with open(self.path) as fh:
            return [AuditedEvent(**json.loads(line)) for line in fh if line.strip()]


def observe_query(store, type_name, plan, t0, t1, t2, result, audit_writer):
    """Bump query metrics and emit the audit event (ref AuditWriter +
    micrometer instrumentation); shared by every store implementation and
    guaranteed never to throw into the query path."""
    try:
        from geomesa_tpu.metrics import queries_run, query_seconds
        from geomesa_tpu.resilience import current_degraded
        from geomesa_tpu.tracing import current_trace_id

        queries_run.inc(store=store, type=type_name)
        query_seconds.observe(t2 - t0)
        if audit_writer is not None:
            audit_writer.write(
                AuditedEvent(
                    store=store,
                    type_name=type_name,
                    filter=str(plan.query.filter),
                    planning_ms=(t1 - t0) * 1e3,
                    scanning_ms=(t2 - t1) * 1e3,
                    hits=len(result),
                    trace_id=current_trace_id(),
                    degraded=",".join(current_degraded()),
                )
            )
    except Exception:  # pragma: no cover - observability must not break reads
        pass
