"""Failure-domain isolation and graceful degradation for the serving path.

Ref role: production geo-serving survives partial failure by degrading,
not dying — GeoMesa's layered fallbacks (loose -> exact, stats -> scan)
and the strategy switching "Adaptive Geospatial Joins for Modern
Hardware" motivates [UNVERIFIED - empty reference mount]. PRs 1-6 built
the layers (sched admission, prefetch pipeline, crash-consistent store,
tracing, chunk pre-aggregates); this module threads ONE fault taxonomy
through all of them so a failed device launch, a flaky disk or a
saturated queue turns into a retried, degraded or typed answer instead
of an unhandled 500.

Three pieces:

- **Fault taxonomy.** :func:`classify` maps any exception on the serving
  path to ``RETRYABLE`` (transient — I/O hiccups, injected
  ``FailpointError``, non-OOM device runtime errors: retry with jittered
  backoff), ``DEGRADABLE`` (the work is lost but a cheaper rung can still
  answer — device OOM, a stuck launch, a corrupt/unreachable partition)
  or ``FATAL`` (bad requests, programming errors, and the typed
  flow-control signals 429/504 which must reach the client untouched).

- **Per-domain circuit breakers.** :class:`CircuitBreaker` instances for
  the ``device`` (launch failures), ``cache`` (resident staging) and
  ``partition`` (per-partition reads, keyed) domains: ``closed`` until
  ``resilience.breaker.failures`` consecutive failures, then ``open``
  (callers skip the domain and take the degradation rung immediately —
  no queueing behind a dead device) for ``resilience.breaker.cooldown.s``,
  then ``half-open`` — ONE probe request is let through; success closes
  the breaker, failure re-opens it.

- **Degradation accounting.** Any layer that answers below the requested
  rung calls :func:`note_degraded` with a bounded reason enum; the server
  installs a collector per request (:func:`collect_degraded`) and stamps
  the reasons into the ``X-Degraded`` response header and the audit
  event. The collector crosses the scheduler's worker threads explicitly
  (:func:`capture_degraded` / :func:`attach_degraded`), exactly like
  tracing contexts.

The ladder itself lives where the knowledge lives: the server falls
resident -> store path when the device or cache domain is unhealthy,
the planner-facing store paths fall exact -> chunk-pushdown under
brownout (:func:`brownout` consults scheduler saturation), and the FS
store serves partial results (stamped degraded) around an unreachable
partition. Everything is gated by ``resilience.enabled`` /
``resilience.degrade`` and observable via the ``geomesa_resilience_*``
metrics and ``/readyz``.
"""

from __future__ import annotations

import random
import time
from contextlib import contextmanager

import contextvars

from geomesa_tpu.locking import checked_lock

__all__ = [
    "RETRYABLE",
    "DEGRADABLE",
    "FATAL",
    "CircuitBreaker",
    "LaunchStuckError",
    "PartitionUnavailableError",
    "attach_degraded",
    "breaker",
    "brownout",
    "capture_degraded",
    "classify",
    "collect_degraded",
    "current_degraded",
    "degrade_allowed",
    "device_breaker",
    "cache_breaker",
    "enabled",
    "is_oom",
    "note_degraded",
    "partition_breaker",
    "reset",
    "retry_call",
    "snapshot",
]

RETRYABLE = "retryable"
DEGRADABLE = "degradable"
FATAL = "fatal"

#: breaker-state gauge encoding (geomesa_resilience_breaker_state)
_STATE_CODE = {"closed": 0, "half-open": 1, "open": 2}


class LaunchStuckError(RuntimeError):
    """A device launch exceeded the watchdog budget: the request is
    failed (or degraded) so the submitter unblocks; the wedged worker
    thread is abandoned and replaced (device launches cannot be
    cancelled mid-flight)."""


class PartitionUnavailableError(RuntimeError):
    """Reads of ONE partition keep failing (retries exhausted or its
    breaker is open): a partition-scoped fault — the rest of the
    dataset keeps serving (degraded) or the query fails typed, never a
    pipeline teardown."""

    def __init__(self, type_name: str, pid, cause: str):
        super().__init__(
            f"dataset {type_name!r} partition {pid} is unavailable: {cause}"
        )
        self.type_name = type_name
        self.pid = pid


def enabled() -> bool:
    from geomesa_tpu.conf import sys_prop

    return bool(sys_prop("resilience.enabled"))


def degrade_allowed() -> bool:
    """Whether degraded (approximate/partial, stamped) answers may be
    served instead of failing — the ``resilience.degrade`` knob on top
    of the master ``resilience.enabled`` switch."""
    from geomesa_tpu.conf import sys_prop

    return enabled() and bool(sys_prop("resilience.degrade"))


# -- fault taxonomy ---------------------------------------------------------


def is_oom(exc: BaseException) -> bool:
    """Device/host memory exhaustion — XLA surfaces HBM OOM as
    RESOURCE_EXHAUSTED XlaRuntimeErrors; staging can also hit host
    MemoryError. OOM is special-cased by the scan paths: halve the
    batch and retry before degrading."""
    if isinstance(exc, MemoryError):
        return True
    s = str(exc)
    return (
        "RESOURCE_EXHAUSTED" in s
        or "Out of memory" in s
        or "out of memory" in s
    )


def classify(exc: BaseException) -> str:
    """Map a serving-path exception to its fault class (module
    docstring). Flow-control signals (429 RejectedError, 504
    DeadlineExpired) are FATAL here on purpose: they are the
    backpressure contract with the client and must never be retried or
    degraded away server-side."""
    from geomesa_tpu.sched.scheduler import DeadlineExpired, RejectedError

    if isinstance(exc, (RejectedError, DeadlineExpired)):
        return FATAL
    if isinstance(exc, (LaunchStuckError, PartitionUnavailableError)):
        return DEGRADABLE
    if is_oom(exc):
        return DEGRADABLE
    try:
        from geomesa_tpu.store.fs import PartitionCorruptError

        if isinstance(exc, PartitionCorruptError):
            return DEGRADABLE
    except ImportError:  # pragma: no cover - fs always importable here
        pass
    if isinstance(exc, FileNotFoundError):
        return FATAL  # a real state (GC'd generation) -- refresh, not retry
    if isinstance(exc, OSError):
        return RETRYABLE  # incl. FailpointError -- transient injection
    if type(exc).__name__ == "XlaRuntimeError":
        return RETRYABLE  # transient device runtime fault (non-OOM)
    if isinstance(exc, (ValueError, KeyError, TypeError)):
        return FATAL  # bad request / programming error: surface loudly
    return FATAL


# -- bounded jittered retry -------------------------------------------------

_rng = random.Random()


def backoff_sleeps(retries: int, base_ms: float, cap_ms: float):
    """Yield jittered exponential backoff sleeps (seconds): the k-th is
    ``base * 2^k`` scaled by a uniform [0.5, 1.5) jitter factor — a
    fleet of clients retrying the same fault decorrelates instead of
    re-spiking in lockstep. ``cap_ms > 0`` bounds the CUMULATIVE sleep:
    the generator stops once the budget is spent, so a flapping
    dependency can never stall a worker for unbounded wall-clock."""
    total = 0.0
    base = max(float(base_ms), 0.0)
    for attempt in range(max(int(retries), 0)):
        d = base * (1 << attempt) * (0.5 + _rng.random())
        # d == 0 (base 0: immediate retries) consumes no budget and must
        # not trip the exhaustion check — the retry COUNT still bounds it
        if cap_ms > 0 and d > 0:
            d = min(d, cap_ms - total)
            if d <= 0:
                return
        total += d
        yield d / 1e3


def retry_call(fn, domain: str = "device"):
    """Run ``fn()`` with bounded jittered-backoff retries of RETRYABLE
    faults (``resilience.retries`` x ``resilience.backoff.ms``, doubling,
    cumulative-capped by ``resilience.backoff.cap.ms``). Non-retryable
    faults — and the final retryable one — propagate to the caller,
    which classifies and degrades/fails."""
    from geomesa_tpu.conf import sys_prop

    if not enabled():
        return fn()
    sleeps = backoff_sleeps(
        int(sys_prop("resilience.retries")),
        float(sys_prop("resilience.backoff.ms")),
        float(sys_prop("resilience.backoff.cap.ms")),
    )
    while True:
        try:
            return fn()
        except Exception as e:
            if classify(e) != RETRYABLE:
                raise
            delay = next(sleeps, None)
            if delay is None:
                raise  # retry budget exhausted: the caller degrades
            from geomesa_tpu import ledger, metrics

            metrics.resilience_retries.inc(domain=domain)
            ledger.charge("retries", 1)
            time.sleep(delay)


# -- circuit breakers -------------------------------------------------------


class CircuitBreaker:
    """Per-domain failure isolation (see the module docstring's state
    machine). Thread-safe; durations are monotonic. ``domain`` is the
    BOUNDED metric label ("device" / "cache" / "partition"); keyed
    instances (per-partition) share their domain's label."""

    def __init__(
        self,
        name: str,
        domain: "str | None" = None,
        failures: "int | None" = None,
        cooldown_s: "float | None" = None,
    ):
        self.name = name
        self.domain = domain or name
        # None = resolve from the resilience.* properties PER USE, so a
        # runtime re-tune (or a test's prop_override) applies to
        # breakers that already exist
        self._failures = None if failures is None else int(failures)
        self._cooldown_s = None if cooldown_s is None else float(cooldown_s)
        self._lock = checked_lock(f"resilience.breaker.{domain or name}")
        self._state = "closed"
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_at = 0.0
        self.opens = 0  # lifetime open transitions (snapshot)

    @property
    def failures(self) -> int:
        if self._failures is not None:
            return self._failures
        from geomesa_tpu.conf import sys_prop

        return int(sys_prop("resilience.breaker.failures"))

    @property
    def cooldown_s(self) -> float:
        if self._cooldown_s is not None:
            return self._cooldown_s
        from geomesa_tpu.conf import sys_prop

        return float(sys_prop("resilience.breaker.cooldown.s"))

    # call under self._lock
    def _transition_locked(self, to: str) -> None:
        if to == self._state:
            return
        self._state = to
        if to == "open":
            self.opens += 1
            self._opened_at = time.monotonic()
        from geomesa_tpu import metrics

        metrics.resilience_breaker_transitions.inc(
            domain=self.domain, to=to
        )
        if self.domain in ("device", "cache"):
            # singleton domains publish their state directly; the keyed
            # partition domain publishes open-breaker counts instead
            metrics.resilience_breaker_state.set(
                _STATE_CODE[to], domain=self.domain
            )

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def allow(self) -> bool:
        """May a request use this domain right now? True while closed.
        While open: False until the cooldown elapses, then the breaker
        half-opens and exactly ONE caller gets True (the probe; a probe
        that never reports back frees the slot after another cooldown).
        The winner MUST call :meth:`record_success` or
        :meth:`record_failure` with its outcome."""
        if not enabled():
            return True
        with self._lock:
            if self._state == "closed":
                return True
            now = time.monotonic()
            if self._state == "open":
                if now - self._opened_at < self.cooldown_s:
                    return False
                self._transition_locked("half-open")
                self._probe_at = now
                return True
            # half-open: one probe in flight at a time
            if now - self._probe_at >= self.cooldown_s:
                self._probe_at = now  # probe lost: hand out another
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0
            if self._state != "closed":
                self._transition_locked("closed")

    def release_probe(self) -> None:
        """Give back a half-open probe slot WITHOUT an outcome: the
        probe was shed or deadline-expired before it could exercise the
        domain — flow control, not a health signal either way. The next
        :meth:`allow` hands out a fresh probe immediately instead of
        holding every caller on the degraded rung for another full
        cooldown. No-op unless half-open."""
        with self._lock:
            if self._state == "half-open":
                self._probe_at = time.monotonic() - self.cooldown_s

    def record_failure(self) -> None:
        opened = False
        with self._lock:
            self._consecutive += 1
            if self._state == "half-open":
                self._transition_locked("open")  # failed probe: re-open
                opened = True
            elif (
                self._state == "closed"
                and self._consecutive >= self.failures
            ):
                self._transition_locked("open")
                opened = True
        if opened:
            # postmortem snapshot OUTSIDE the breaker lock (the bundle
            # write is file I/O); rate limiting and the enabled gates
            # live in the recorder
            try:
                from geomesa_tpu import slo

                slo.on_breaker_open(self.domain)
            except Exception:  # pragma: no cover - must not break serving
                pass

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "failure_threshold": self.failures,
                "cooldown_s": self.cooldown_s,
                "opens": self.opens,
            }


_breakers_lock = checked_lock("resilience.breakers")
_breakers: "dict[object, CircuitBreaker]" = {}
#: keyed (per-partition) breakers kept at most this many (hard bound);
#: closed ones are evicted first so an open breaker survives to its
#: half-open whenever anything closed remains to evict instead
_PARTITION_BREAKERS_MAX = 1024


def breaker(domain: str) -> CircuitBreaker:
    """The process-wide breaker for a singleton domain."""
    with _breakers_lock:
        b = _breakers.get(domain)
        if b is None:
            b = _breakers[domain] = CircuitBreaker(domain, domain=domain)
        return b


def device_breaker() -> CircuitBreaker:
    return breaker("device")


def cache_breaker() -> CircuitBreaker:
    return breaker("cache")


def wal_breaker() -> CircuitBreaker:
    """The breaker guarding write-ahead-log I/O (streaming ingest): an
    open breaker fails appends fast — acks must never be promised
    against a log that cannot take them."""
    return breaker("wal")


def partition_breaker(type_name: str, pid) -> CircuitBreaker:
    """The keyed breaker guarding reads of ONE partition. Bounded
    registry (HARD bound): when full, closed keyed breakers evict
    insertion-order first (open ones keep their cooldown state); with
    nothing closed — a store-wide outage — the oldest keyed breaker is
    evicted anyway. Losing an open breaker's state merely means that
    partition's next read probes and re-opens it; unbounded growth
    would be a memory leak sized by the outage."""
    key = ("partition", type_name, pid)
    with _breakers_lock:
        b = _breakers.get(key)
        if b is None:
            keyed = [
                k for k in _breakers if isinstance(k, tuple)
            ]
            if len(keyed) >= _PARTITION_BREAKERS_MAX:
                for k in keyed:
                    if _breakers[k]._state == "closed":
                        del _breakers[k]
                        break
                else:
                    del _breakers[keyed[0]]
            b = _breakers[key] = CircuitBreaker(
                f"partition:{type_name}:{pid}", domain="partition"
            )
        return b


def open_partition_breakers() -> int:
    with _breakers_lock:
        keyed = [
            b for k, b in _breakers.items() if isinstance(k, tuple)
        ]
    return sum(1 for b in keyed if b.state != "closed")


def snapshot() -> dict:
    """Breaker states for ``/readyz`` and ``/stats``-style docs. The
    singleton domains always appear (created closed on first ask) so a
    health probe sees the full domain list from the first scrape."""
    device_breaker()
    cache_breaker()
    wal_breaker()
    with _breakers_lock:
        singles = {
            k: b for k, b in _breakers.items() if isinstance(k, str)
        }
    doc = {k: b.snapshot() for k, b in sorted(singles.items())}
    doc["partition_open"] = open_partition_breakers()
    return doc


def reset() -> None:
    """Drop every breaker and its state (tests / bench isolation)."""
    from geomesa_tpu import metrics

    with _breakers_lock:
        _breakers.clear()
    metrics.resilience_breaker_state.set(0, domain="device")
    metrics.resilience_breaker_state.set(0, domain="cache")
    metrics.resilience_breaker_state.set(0, domain="wal")


# -- degradation accounting -------------------------------------------------

#: the per-request degradation collector; None outside a serving request
_collector: contextvars.ContextVar = contextvars.ContextVar(
    "geomesa_degraded", default=None
)

# observer seams for the runtime context checker (ctxcheck): armed only
# by its install(); None costs one comparison per attach/stamp
_attach_observer = None
_degraded_observer = None


def set_attach_observer(fn) -> None:
    global _attach_observer
    _attach_observer = fn


def set_degraded_observer(fn) -> None:
    global _degraded_observer
    _degraded_observer = fn

#: bounded reason enum (metric label discipline): every note_degraded
#: reason must come from here — an unlisted reason still collects but
#: is counted under "other" so label cardinality stays fixed
REASONS = frozenset(
    {
        "device-breaker-open",
        "device-launch-failed",
        "launch-stuck",
        "device-oom",
        "resident-unavailable",
        "cache-breaker-open",
        "partition-unavailable",
        "brownout-pushdown",
        "mesh-degraded",
        "ingest-degraded",
        "wal-replay-truncated",
        "replica-lag",
        "replica-degraded",
        "reprovision-installing",
    }
)


@contextmanager
def collect_degraded():
    """Install a fresh per-request collector; yields the (mutable,
    ordered, deduplicated) reason list the request accumulated."""
    reasons: list = []
    token = _collector.set(reasons)
    if _attach_observer is not None:
        _attach_observer(reasons, True)
    try:
        yield reasons
    finally:
        if _attach_observer is not None:
            _attach_observer(reasons, False)
        _collector.reset(token)


def note_degraded(reason: str) -> None:
    """Record that the current request was answered below its requested
    rung. Reasons are the bounded enum above; collection is a no-op
    outside a request, the metric always counts."""
    from geomesa_tpu import ledger, metrics

    metrics.resilience_degraded.inc(
        reason=reason if reason in REASONS else "other"
    )
    ledger.charge("degraded", 1)
    reasons = _collector.get()
    if _degraded_observer is not None:
        _degraded_observer(reasons, reason)
    if reasons is not None and reason not in reasons:
        reasons.append(reason)


def current_degraded() -> "list[str]":
    reasons = _collector.get()
    return list(reasons) if reasons else []


def capture_degraded():
    """The current collector, for EXPLICIT propagation onto worker
    threads (contextvars are per-thread — same discipline as
    tracing.capture/attach)."""
    return _collector.get()


@contextmanager
def attach_degraded(reasons):
    """Attach a captured collector around work executing on another
    thread (scheduler workers); None attaches nothing."""
    if reasons is None:
        yield
        return
    token = _collector.set(reasons)
    if _attach_observer is not None:
        _attach_observer(reasons, True)
    try:
        yield
    finally:
        if _attach_observer is not None:
            _attach_observer(reasons, False)
        _collector.reset(token)


def brownout(scheduler) -> bool:
    """Is the serving path under enough load that exact answers should
    yield to cheap pre-aggregated ones? True when the scheduler's
    admission queue is past ``resilience.brownout.queue.frac`` of its
    bound (the 429 cliff is right behind it)."""
    if scheduler is None or not degrade_allowed():
        return False
    from geomesa_tpu.conf import sys_prop

    frac = float(sys_prop("resilience.brownout.queue.frac"))
    if frac <= 0:
        return False
    snap = scheduler.queue_pressure()
    return snap[0] >= frac * max(snap[1], 1)
