// Multi-lane LSD radix argsort -- the flush-path sort kernel.
//
// Ref role: the reference's ingest sorts rows by index key before bulk
// import (MapReduce bulk sort / local sorted batches [UNVERIFIED - empty
// reference mount]). The single-host rebuild path sorts (bin, z_hi, z_lo)
// uint32 lanes; numpy's lexsort is a comparison sort (~1.1s for 2^22
// rows), while digit-wise LSD counting sort is linear.
//
// Two structural savings over the textbook version:
//  - 16-bit digits: two stable counting passes per uint32 lane, not four.
//  - histograms are order-independent (a counting sort's digit counts
//    don't depend on the current permutation), so ALL digit histograms
//    are computed in one sequential sweep per lane up front; passes whose
//    digit is constant across the batch (the bin lane's high half, any
//    dead key bits) are skipped entirely.
//
// Contract (mirrors geomesa_tpu.index.build._sort_order): stable,
// lexicographic by lanes with lane 0 MOST significant; equal full keys
// keep input order. Output is the permutation (argsort), int64.

#include <cstdint>
#include <cstring>
#include <vector>

namespace {
constexpr int kDigitBits = 16;
constexpr int kBuckets = 1 << kDigitBits;  // 65536
}

extern "C" {

// lanes: n_lanes * n uint32 values, lane-major (lane 0 first in memory,
// lane 0 = MOST significant). order_out: n int64 indices.
void gm_radix_argsort(int64_t n, int32_t n_lanes, const uint32_t* lanes,
                      int64_t* order_out) {
    if (n <= 0) return;
    std::vector<uint32_t> idx_a(static_cast<size_t>(n));
    std::vector<uint32_t> idx_b(static_cast<size_t>(n));
    uint32_t* cur = idx_a.data();
    uint32_t* nxt = idx_b.data();
    for (int64_t i = 0; i < n; ++i) cur[i] = static_cast<uint32_t>(i);

    std::vector<size_t> pos(kBuckets);
    std::vector<size_t> hist_lo(kBuckets), hist_hi(kBuckets);

    // LSD: least-significant lane first, low digit before high digit;
    // every pass is a stable counting sort, so the final order is the
    // stable lexicographic sort of the full multi-lane key.
    for (int32_t lane = n_lanes - 1; lane >= 0; --lane) {
        const uint32_t* v = lanes + static_cast<size_t>(lane) * n;
        // one sequential sweep fills both digit histograms (counts are
        // permutation-independent)
        std::memset(hist_lo.data(), 0, kBuckets * sizeof(size_t));
        std::memset(hist_hi.data(), 0, kBuckets * sizeof(size_t));
        for (int64_t i = 0; i < n; ++i) {
            uint32_t x = v[i];
            ++hist_lo[x & 0xFFFF];
            ++hist_hi[x >> 16];
        }
        for (int half = 0; half < 2; ++half) {
            const std::vector<size_t>& h = half == 0 ? hist_lo : hist_hi;
            const int shift = half == 0 ? 0 : 16;
            // a digit constant across the batch orders nothing: skip
            int nonzero = 0;
            for (int b = 0; b < kBuckets && nonzero < 2; ++b)
                if (h[b]) ++nonzero;
            if (nonzero < 2) continue;
            size_t run = 0;
            for (int b = 0; b < kBuckets; ++b) { pos[b] = run; run += h[b]; }
            for (int64_t i = 0; i < n; ++i) {
                uint32_t r = cur[i];
                nxt[pos[(v[r] >> shift) & 0xFFFF]++] = r;
            }
            uint32_t* t = cur; cur = nxt; nxt = t;
        }
    }
    for (int64_t i = 0; i < n; ++i) order_out[i] = cur[i];
}

}  // extern "C"

