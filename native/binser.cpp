// Native batch decoder for the binary feature row format
// (geomesa_tpu/features/binser.py -- the KryoFeatureSerializer-analog KV
// value layout). Decodes whole columns across many rows in one pass: the
// KV-store scan hot loop (ref role: the tablet-server side of
// FilterTransformIterator's lazy Kryo decode, done columnar).
//
// Layout per row (little-endian):
//   u8 version(=1) | u8 flags | fid(kind u8: 0 zigzag-varint, 1 len-str)
//   u16 n_attrs | u32 x (n_attrs+1) payload offset table | payloads
//   payload: u8 0=null else 1 + typed bytes
//
// Exposed entry points return 0 on success, or -(row_index+1) on a
// malformed row so Python can fall back and report.

#include <cstdint>
#include <cstring>

namespace {

inline bool read_varint(const uint8_t* p, uint64_t end, uint64_t* pos,
                        uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (*pos < end && shift < 64) {
    uint8_t b = p[(*pos)++];
    v |= (uint64_t)(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;
}

inline int64_t unzigzag(uint64_t v) {
  return (int64_t)(v >> 1) ^ -(int64_t)(v & 1);
}

}  // namespace

extern "C" {

// Parse every row's header. Outputs:
//   payload_base[i]: absolute offset of row i's payload area
//   fids_int[i]    : integer fid (when fid kind is 0)
//   fid_off/fid_len: utf-8 span of string fids (when kind is 1)
//   flags_out[i]   : bit0 = string fid, bit1 = has user-data section
int binser_headers(const uint8_t* data, const uint64_t* row_off, int64_t n,
                   int32_t n_attrs, uint64_t* payload_base, int64_t* fids_int,
                   uint64_t* fid_off, uint32_t* fid_len, uint8_t* flags_out) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t pos = row_off[i], end = row_off[i + 1];
    if (pos + 3 > end || data[pos] != 1) return -(int)(i + 1);
    uint8_t row_flags = data[pos + 1];
    uint8_t kind = data[pos + 2];
    pos += 3;
    uint8_t flags = (row_flags & 0x01) ? 2 : 0;
    if (kind == 0) {
      uint64_t raw;
      if (!read_varint(data, end, &pos, &raw)) return -(int)(i + 1);
      fids_int[i] = unzigzag(raw);
      fid_off[i] = 0;
      fid_len[i] = 0;
    } else {
      uint64_t len;
      if (!read_varint(data, end, &pos, &len)) return -(int)(i + 1);
      if (pos + len > end) return -(int)(i + 1);
      fid_off[i] = pos;
      fid_len[i] = (uint32_t)len;
      fids_int[i] = 0;
      flags |= 1;
      pos += len;
    }
    if (pos + 2 > end) return -(int)(i + 1);
    uint16_t count;
    std::memcpy(&count, data + pos, 2);
    pos += 2;
    if (count != (uint16_t)n_attrs) return -(int)(i + 1);
    uint64_t tbl_bytes = 4ull * (n_attrs + 1);
    if (pos + tbl_bytes > end) return -(int)(i + 1);
    payload_base[i] = pos + tbl_bytes;
    flags_out[i] = flags;
  }
  return 0;
}

// Decode one attribute across all rows.
//   code 0: zigzag varint -> int64 out
//   code 1: f32 out   code 2: f64 out   code 3: bool -> u8 out
//   code 4: WKB point -> f64 out[(i,0)=x,(i,1)=y]
//   code 5: string -> (str_off, str_len) spans into data
// nulls[i] set to 1 for null payloads (outputs left zeroed).
int binser_column(const uint8_t* data, const uint64_t* row_off,
                  const uint64_t* payload_base, int64_t n, int32_t n_attrs,
                  int32_t attr, int32_t code, void* out, uint64_t* str_off,
                  uint32_t* str_len, uint8_t* nulls) {
  for (int64_t i = 0; i < n; i++) {
    uint64_t base = payload_base[i];
    uint64_t tbl = base - 4ull * (n_attrs + 1);
    uint32_t o0, o1;
    std::memcpy(&o0, data + tbl + 4ull * attr, 4);
    std::memcpy(&o1, data + tbl + 4ull * (attr + 1), 4);
    uint64_t lo = base + o0, hi = base + o1;
    if (hi > row_off[i + 1] || lo > hi) return -(int)(i + 1);
    nulls[i] = 0;
    if (lo == hi || data[lo] == 0) {
      nulls[i] = 1;
      if (code == 5) {
        str_off[i] = 0;
        str_len[i] = 0;
      }
      continue;
    }
    lo += 1;  // skip the non-null marker
    switch (code) {
      case 0: {  // zigzag varint (Integer/Long/Date)
        uint64_t raw, pos = lo;
        if (!read_varint(data, hi, &pos, &raw)) return -(int)(i + 1);
        ((int64_t*)out)[i] = unzigzag(raw);
        break;
      }
      case 1: {
        if (hi - lo < 4) return -(int)(i + 1);
        std::memcpy((float*)out + i, data + lo, 4);
        break;
      }
      case 2: {
        if (hi - lo < 8) return -(int)(i + 1);
        std::memcpy((double*)out + i, data + lo, 8);
        break;
      }
      case 3: {
        if (lo >= hi) return -(int)(i + 1);
        ((uint8_t*)out)[i] = data[lo] == 1 ? 1 : 0;
        break;
      }
      case 4: {  // WKB point: byteorder u8 | u32 type | f64 x | f64 y
        if (hi - lo < 21 || data[lo] != 1) return -(int)(i + 1);
        uint32_t gtype;
        std::memcpy(&gtype, data + lo + 1, 4);
        if (gtype != 1) return -(int)(i + 1);
        std::memcpy((double*)out + 2 * i, data + lo + 5, 8);
        std::memcpy((double*)out + 2 * i + 1, data + lo + 13, 8);
        break;
      }
      case 5: {  // string span
        str_off[i] = lo;
        str_len[i] = (uint32_t)(hi - lo);
        break;
      }
      default:
        return -(int)(i + 1);
    }
  }
  return 0;
}

}  // extern "C"
