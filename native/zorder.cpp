// Native host kernels for geomesa-tpu: bulk Morton encode/decode and the
// litmax/bigmin z-range decomposition.
//
// Semantics are EXACTLY those of geomesa_tpu/curves/zorder.py and
// zranges.py (the Python implementations are the oracle; tests assert
// bit-identical output). The range decomposition is the client-side hot
// loop of the reference's query path (SURVEY.md section 3.1): recursive
// quad/oct-tree pruning that the JVM reference does per-query in Scala
// (sfcurve ZN.zranges) and we do here in C++ at ~20-50x the Python speed.
//
// Build: make -C native   (g++ -O3 -shared -fPIC)
// Python binding: ctypes (geomesa_tpu/native.py) -- no pybind11 in image.

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <deque>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// Morton encode/decode (magic-mask gather/scatter; matches zorder.py masks)
// ---------------------------------------------------------------------------

static inline uint64_t split2(uint64_t x) {
  x &= 0x7fffffffULL;
  x = (x ^ (x << 32)) & 0x00000000ffffffffULL;
  x = (x ^ (x << 16)) & 0x0000ffff0000ffffULL;
  x = (x ^ (x << 8)) & 0x00ff00ff00ff00ffULL;
  x = (x ^ (x << 4)) & 0x0f0f0f0f0f0f0f0fULL;
  x = (x ^ (x << 2)) & 0x3333333333333333ULL;
  x = (x ^ (x << 1)) & 0x5555555555555555ULL;
  return x;
}

static inline uint64_t combine2(uint64_t z) {
  uint64_t x = z & 0x5555555555555555ULL;
  x = (x ^ (x >> 1)) & 0x3333333333333333ULL;
  x = (x ^ (x >> 2)) & 0x0f0f0f0f0f0f0f0fULL;
  x = (x ^ (x >> 4)) & 0x00ff00ff00ff00ffULL;
  x = (x ^ (x >> 8)) & 0x0000ffff0000ffffULL;
  x = (x ^ (x >> 16)) & 0x00000000ffffffffULL;
  x = (x ^ (x >> 32)) & 0x7fffffffULL;
  return x;
}

static inline uint64_t split3(uint64_t x) {
  x &= 0x1fffffULL;
  x = (x | (x << 32)) & 0x1f00000000ffffULL;
  x = (x | (x << 16)) & 0x1f0000ff0000ffULL;
  x = (x | (x << 8)) & 0x100f00f00f00f00fULL;
  x = (x | (x << 4)) & 0x10c30c30c30c30c3ULL;
  x = (x | (x << 2)) & 0x1249249249249249ULL;
  return x;
}

static inline uint64_t combine3(uint64_t z) {
  uint64_t x = z & 0x1249249249249249ULL;
  x = (x ^ (x >> 2)) & 0x10c30c30c30c30c3ULL;
  x = (x ^ (x >> 4)) & 0x100f00f00f00f00fULL;
  x = (x ^ (x >> 8)) & 0x1f0000ff0000ffULL;
  x = (x ^ (x >> 16)) & 0x1f00000000ffffULL;
  x = (x ^ (x >> 32)) & 0x1fffffULL;
  return x;
}

void gm_encode_2d(int64_t n, const uint64_t* x, const uint64_t* y,
                  uint64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = split2(x[i]) | (split2(y[i]) << 1);
}

void gm_decode_2d(int64_t n, const uint64_t* z, uint64_t* x, uint64_t* y) {
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine2(z[i]);
    y[i] = combine2(z[i] >> 1);
  }
}

void gm_encode_3d(int64_t n, const uint64_t* x, const uint64_t* y,
                  const uint64_t* t, uint64_t* out) {
  for (int64_t i = 0; i < n; ++i)
    out[i] = split3(x[i]) | (split3(y[i]) << 1) | (split3(t[i]) << 2);
}

void gm_decode_3d(int64_t n, const uint64_t* z, uint64_t* x, uint64_t* y,
                  uint64_t* t) {
  for (int64_t i = 0; i < n; ++i) {
    x[i] = combine3(z[i]);
    y[i] = combine3(z[i] >> 1);
    t[i] = combine3(z[i] >> 2);
  }
}

// ---------------------------------------------------------------------------
// Quantize + encode fused (the ingest-side per-feature key hot loop)
// ---------------------------------------------------------------------------

static inline uint64_t quantize(double v, double lo, double hi, int64_t bins) {
  if (v >= hi) return (uint64_t)(bins - 1);
  double scale = (double)bins / (hi - lo);
  int64_t idx = (int64_t)std::floor((v - lo) * scale);
  if (idx < 0) idx = 0;
  if (idx > bins - 1) idx = bins - 1;
  return (uint64_t)idx;
}

void gm_z3_index(int64_t n, const double* x, const double* y, const double* t,
                 double t_max, uint64_t* out) {
  const int64_t bins = 1LL << 21;
  for (int64_t i = 0; i < n; ++i) {
    uint64_t nx = quantize(x[i], -180.0, 180.0, bins);
    uint64_t ny = quantize(y[i], -90.0, 90.0, bins);
    uint64_t nt = quantize(t[i], 0.0, t_max, bins);
    out[i] = split3(nx) | (split3(ny) << 1) | (split3(nt) << 2);
  }
}

// ---------------------------------------------------------------------------
// zranges: level-order BFS binary descent (mirrors zranges.py exactly)
// ---------------------------------------------------------------------------

struct Node {
  uint64_t zprefix;
  int decided;
  uint64_t dp[3];
};

struct Range {
  uint64_t lo, hi;
  uint8_t contained;
};

static inline int decided_for_dim(int decided, int d, int dims,
                                  int total_bits) {
  // count of b in [total_bits-decided, total_bits-1] with b % dims == d
  if (decided == 0) return 0;
  int lo_b = total_bits - decided;
  int hi_b = total_bits - 1;
  // floor divisions with potentially negative numerators (match Python //)
  auto fdiv = [](int a, int b) {
    int q = a / b;
    if ((a % b != 0) && ((a < 0) != (b < 0))) --q;
    return q;
  };
  return fdiv(hi_b - d, dims) - fdiv(lo_b - 1 - d, dims);
}

// returns number of ranges written, or -1 if out_cap insufficient
int64_t gm_zranges(const uint64_t* qlo, const uint64_t* qhi, int dims,
                   int bits_per_dim, int64_t max_ranges, int max_bits,
                   uint64_t* out_lo, uint64_t* out_hi, uint8_t* out_contained,
                   int64_t out_cap) {
  const int total_bits = dims * bits_per_dim;
  for (int d = 0; d < dims; ++d)
    if (qhi[d] < qlo[d]) return 0;
  if (max_bits < 0 || max_bits > total_bits) max_bits = total_bits;

  std::vector<Range> results;
  std::vector<Range> overflow;
  std::deque<Node> queue;
  queue.push_back(Node{0, 0, {0, 0, 0}});

  while (!queue.empty()) {
    Node node = queue.front();
    queue.pop_front();
    int rem = total_bits - node.decided;
    bool contained = true, disjoint = false;
    for (int d = 0; d < dims; ++d) {
      int dec_d = decided_for_dim(node.decided, d, dims, total_bits);
      int r = bits_per_dim - dec_d;
      uint64_t lo_d = node.dp[d] << r;
      uint64_t hi_d = lo_d + ((r >= 64 ? 0 : (1ULL << r)) - 1);
      if (hi_d < qlo[d] || lo_d > qhi[d]) {
        disjoint = true;
        break;
      }
      if (!(lo_d >= qlo[d] && hi_d <= qhi[d])) contained = false;
    }
    if (disjoint) continue;
    uint64_t zlo = node.zprefix << rem;
    uint64_t zhi = zlo + ((rem >= 64 ? 0 : (1ULL << rem)) - 1);
    if (contained) {
      results.push_back(Range{zlo, zhi, 1});
      continue;
    }
    int64_t budget_left = max_ranges - (int64_t)results.size() -
                          (int64_t)overflow.size() - (int64_t)queue.size();
    if (rem == 0 || node.decided >= max_bits || budget_left <= 0) {
      overflow.push_back(Range{zlo, zhi, 0});
      continue;
    }
    int d = (total_bits - 1 - node.decided) % dims;
    Node c0 = node, c1 = node;
    c0.zprefix = node.zprefix << 1;
    c1.zprefix = (node.zprefix << 1) | 1;
    c0.decided = c1.decided = node.decided + 1;
    c0.dp[d] = node.dp[d] << 1;
    c1.dp[d] = (node.dp[d] << 1) | 1;
    queue.push_back(c0);
    queue.push_back(c1);
  }

  results.insert(results.end(), overflow.begin(), overflow.end());
  std::sort(results.begin(), results.end(),
            [](const Range& a, const Range& b) { return a.lo < b.lo; });

  // coalesce adjacent/overlapping (z <= 2^63-1, so hi+1 cannot wrap)
  std::vector<Range> merged;
  for (const Range& r : results) {
    if (!merged.empty() && r.lo <= merged.back().hi + 1) {
      merged.back().hi = std::max(merged.back().hi, r.hi);
      merged.back().contained = merged.back().contained && r.contained;
    } else {
      merged.push_back(r);
    }
  }
  // enforce budget by merging smallest gaps
  while ((int64_t)merged.size() > max_ranges) {
    size_t best = 0;
    uint64_t best_gap = UINT64_MAX;
    for (size_t i = 0; i + 1 < merged.size(); ++i) {
      uint64_t gap = merged[i + 1].lo - merged[i].hi;
      if (gap < best_gap) {
        best_gap = gap;
        best = i;
      }
    }
    merged[best].hi = merged[best + 1].hi;
    merged[best].contained = 0;
    merged.erase(merged.begin() + best + 1);
  }

  if ((int64_t)merged.size() > out_cap) return -1;
  for (size_t i = 0; i < merged.size(); ++i) {
    out_lo[i] = merged[i].lo;
    out_hi[i] = merged[i].hi;
    out_contained[i] = merged[i].contained;
  }
  return (int64_t)merged.size();
}

}  // extern "C"
