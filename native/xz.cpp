// Native host kernel for the XZ extent-curve encode (non-point geometry
// keys): the per-feature pre-order quad/octree walk of
// geomesa_tpu/curves/xz.py, bit-identical by construction — same IEEE
// double ops in the same order (frexp-exact level, power-of-two cell
// widths via ldexp, the corner-descent walk). The Python implementation
// is the oracle; tests assert exact equality.
//
// This is the ingest-side hot loop for polygon/line schemas (the XZ2/XZ3
// analog of gm_z3_index): host staging and FS-store index builds encode
// every row's envelope here when the device encode is unavailable.

#include <cmath>
#include <cstdint>

extern "C" {

// mins/maxs: dims contiguous arrays of n doubles each, laid out
// [dim0[0..n), dim1[0..n), ...] (the (dims, n) C-order numpy layout),
// already normalized to [0, 1] and validated (maxs >= mins) by the
// caller. out: n int64 sequence codes. dims in {2, 3}; g <= 31 (2D) /
// 20 (3D) so the code space fits int64 (validated Python-side).
void gm_xz_index(int64_t n, int32_t dims, int32_t g, const double* mins,
                 const double* maxs, int64_t* out) {
  const int64_t fanout = 1LL << dims;
  // child_step[i] = (fanout^(g-i) - 1) / (fanout - 1)
  int64_t child_step[32];
  for (int32_t i = 0; i < g; ++i) {
    int64_t p = 1;
    for (int32_t k = 0; k < g - i; ++k) p *= fanout;
    child_step[i] = (p - 1) / (fanout - 1);
  }
  for (int64_t r = 0; r < n; ++r) {
    double mn[3], mx[3];
    double w = 0.0;
    for (int32_t d = 0; d < dims; ++d) {
      double a = mins[d * n + r];
      double b = maxs[d * n + r];
      if (a < 0.0) a = 0.0;
      if (a > 1.0) a = 1.0;
      if (b < 0.0) b = 0.0;
      if (b > 1.0) b = 1.0;
      mn[d] = a;
      mx[d] = b;
      double e = b - a;
      if (d == 0 || e > w) w = e;
    }
    // l1 = floor(log2(1/w)), exact via the float exponent (numpy frexp
    // semantics: w = m * 2^e, m in [0.5, 1))
    int32_t l1;
    if (w <= 0.0) {
      l1 = g;
    } else {
      int e;
      double m = std::frexp(w, &e);
      l1 = (m == 0.5) ? (1 - e) : -e;
      if (l1 > g) l1 = g;
    }
    // fit one level deeper? w2 = 0.5^min(l1+1, g), an exact power of two
    int32_t k2 = l1 + 1 < g ? l1 + 1 : g;
    double w2 = std::ldexp(1.0, -k2);
    bool fits = true;
    for (int32_t d = 0; d < dims; ++d) {
      if (!(mx[d] <= std::floor(mn[d] / w2) * w2 + 2.0 * w2)) {
        fits = false;
        break;
      }
    }
    int32_t length = (l1 < g && fits) ? l1 + 1 : l1;
    if (length < 0) length = 0;
    if (length > g) length = g;
    // pre-order walk: descend toward the box corner, accumulating the
    // sequence code
    double lo[3] = {0.0, 0.0, 0.0};
    double hi[3] = {1.0, 1.0, 1.0};
    int64_t cs = 0;
    for (int32_t i = 0; i < length; ++i) {
      int64_t quad = 0;
      for (int32_t d = 0; d < dims; ++d) {
        double center = (lo[d] + hi[d]) * 0.5;
        if (mn[d] >= center) {
          quad |= (1LL << d);
          lo[d] = center;
        } else {
          hi[d] = center;
        }
      }
      cs += 1 + quad * child_step[i];
    }
    out[r] = cs;
  }
}

}  // extern "C"
